"""Multi-host runtime bootstrap.

Capability parity with the reference's multi-node bootstrap: gen_nccl_id over
gRPC (reference: paddle/fluid/operators/gen_nccl_id_op.cc:31-59,
platform/nccl_helper.h:96-120 NCCLContextMap with num_trainers*places ranks)
and the PADDLE_* role env protocol (reference: python/paddle/fluid/
trainer.py:321-369).

TPU-native redesign: `jax.distributed.initialize` performs the id-exchange/
rendezvous over DCN (coordinator = trainer 0), after which `jax.devices()`
spans every host's chips and a Mesh over them gives GSPMD collectives across
ICI within a slice and DCN between slices. The PADDLE_* env variables are
honored so reference launch scripts keep working.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None):
    """Join the multi-host world. Defaults follow the reference env protocol:
    PADDLE_TRAINER_ID -> process_id, PADDLE_TRAINERS -> num_processes,
    PADDLE_TRAINER_ENDPOINTS (or PADDLE_PSERVER_IPS:port) -> coordinator =
    first endpoint."""
    global _initialized
    if _initialized:
        return
    process_id = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    num_processes = num_processes if num_processes is not None else \
        int(os.environ.get("PADDLE_TRAINERS", "1"))
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator_address = eps.split(",")[0] if eps else "127.0.0.1:8273"
    if num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def get_world_size() -> int:
    return jax.process_count()


def get_rank() -> int:
    return jax.process_index()


def global_mesh(axis_names=("dp",), axis_sizes=None):
    """Mesh over every device in the (multi-host) world — the NCCLContextMap
    `num_trainers * places` world (reference nccl_helper.h:118)."""
    from .parallel.mesh import make_mesh
    devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = [len(devices)]
    return make_mesh(axis_sizes, axis_names, devices)


def barrier():
    """Host barrier (reference fetch_barrier/send_barrier analog)."""
    if jax.process_count() > 1:
        # effects a cross-host sync via a tiny all-reduce
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = global_mesh()
        x = jax.device_put(jnp.zeros(len(jax.devices())),
                           NamedSharding(mesh, PartitionSpec("dp")))
        jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, PartitionSpec()))(x).block_until_ready()


def shard_local_batch(arr, mesh=None, axis="dp"):
    """Build a GLOBAL batch-sharded array from this host's LOCAL batch —
    the production multi-host feeding pattern (each trainer reads its own
    data shard; the reference's trainers likewise each read a file split,
    trainer.py train_reader slicing). The global batch dim is
    world_local_sum of the per-host dims."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = mesh or global_mesh(axis_names=(axis,))
    spec = [None] * np.ndim(arr)
    spec[0] = axis
    sh = NamedSharding(mesh, PartitionSpec(*spec))
    return jax.make_array_from_process_local_data(sh, np.asarray(arr))
