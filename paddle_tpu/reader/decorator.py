"""Reader decorators (reference: python/paddle/reader/decorator.py:
map_readers :29, shuffle :51, chain :86, compose :118, buffered :165,
firstn :208, xmap_readers :236; minibatch in python/paddle/v2/minibatch.py).

A reader is a zero-arg callable returning an iterator of samples — identical
contract to the reference. `buffered` / `xmap_readers` use threads to overlap
host-side decode with TPU steps (the reference's double-buffer analog lives in
async_feeder.py)."""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable, List


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            # reference semantics (decorator.py:135): alignment CHECKED ->
            # misaligned readers raise ComposeNotAligned
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            # unchecked: silently stop at the shortest reader
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader, size):
    """Background-thread prefetch buffer (overlaps host IO with device steps)."""

    class _End:
        pass

    def data_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def producer():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads
    (reference decorator.py:236)."""

    end = object()

    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feeder():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feeder, daemon=True).start()
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            i, mapped = item
            if order:
                pending[i] = mapped
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            else:
                yield mapped
        if order:
            while next_idx in pending:
                yield pending.pop(next_idx)
                next_idx += 1

    return data_reader


def cache(reader):
    all_data = []
    lock = threading.Lock()
    done = [False]

    def data_reader():
        with lock:
            if not done[0]:
                all_data.extend(reader())
                done[0] = True
        yield from all_data

    return data_reader


def batch(reader, batch_size, drop_last=True):
    """Group samples into lists (reference v2/minibatch.py). drop_last
    defaults True on TPU: constant shapes avoid re-jits."""

    def batch_reader():
        b = []
        for inst in reader():
            b.append(inst)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


class ComposeNotAligned(ValueError):
    """Raised by compose(check_alignment=True) when input readers yield
    different numbers of samples (reference decorator.py:114)."""


class PipeReader:
    """Stream records from a shell command's stdout (reference
    decorator.py PipeReader): `PipeReader("cat f.txt").get_line()` yields
    decoded lines; file_type="gzip" decompresses on the fly. The command
    is run WITHOUT a shell (split argv), matching the reference."""

    def __init__(self, command, bufsize=8192, file_type="plain"):
        import subprocess
        import zlib
        if not isinstance(command, str):
            raise TypeError("command must be a string")
        if file_type == "gzip":
            self.dec = zlib.decompressobj(32 + zlib.MAX_WBITS)
        elif file_type != "plain":
            raise TypeError(f"file_type {file_type} is not allowed")
        self.file_type = file_type
        self.bufsize = bufsize
        self.process = subprocess.Popen(command.split(" "), bufsize=bufsize,
                                        stdout=subprocess.PIPE)

    def get_line(self, cut_lines=True, line_break="\n"):
        remained = b""
        lb = line_break.encode() if isinstance(line_break, str) else line_break
        while True:
            buff = self.process.stdout.read(self.bufsize)
            if buff:
                if self.file_type == "gzip":
                    buff = self.dec.decompress(buff)
                if cut_lines:
                    lines = (remained + buff).split(lb)
                    remained = lines.pop()
                    for line in lines:
                        yield line.decode(errors="replace")
                else:
                    yield buff
            else:
                if self.file_type == "gzip":
                    # bytes still buffered in the decompressobj at EOF
                    # would otherwise be dropped (truncated last lines)
                    tail = self.dec.flush()
                    if tail:
                        if cut_lines:
                            remained += tail
                        else:
                            yield tail
                break
        if cut_lines and remained:
            lines = remained.split(lb)
            if lines and lines[-1] == b"":   # trailing line break only
                lines.pop()
            for line in lines:
                yield line.decode(errors="replace")
        elif remained:
            yield remained.decode(errors="replace")
