"""Reader creators (reference: python/paddle/reader/creator.py —
np_array, text_file, recordio)."""

from __future__ import annotations

import glob as _glob
import pickle

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Reader over a numpy array's outermost dimension (reference
    creator.py np_array)."""

    def reader():
        if x.ndim < 1:
            yield x
            return
        for e in x:
            yield e

    return reader


def text_file(path):
    """Reader yielding the file's lines without trailing newlines
    (reference creator.py text_file)."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Reader over RecordIO file(s): a list, a comma-separated string, or
    a glob pattern (reference creator.py recordio). Records are unpickled
    — the format recordio_writer.convert_reader_to_recordio_file emits."""
    from .. import recordio as rio

    if isinstance(paths, str):
        path_list = []
        for p in paths.split(","):
            path_list.extend(sorted(_glob.glob(p)) or [p])
    else:
        path_list = list(paths)

    def reader():
        for p in path_list:
            for rec in rio.reader(p)():
                yield pickle.loads(rec)

    return reader
