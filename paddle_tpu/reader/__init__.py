"""Reader composition toolkit (reference: python/paddle/reader/)."""

from .decorator import (map_readers, shuffle, chain, compose, buffered,  # noqa: F401
                        firstn, xmap_readers, cache, batch,
                        ComposeNotAligned, PipeReader)
from .py_reader import PyReader, py_reader  # noqa: F401
from . import creator  # noqa: F401
