"""py_reader: blocking-queue input pipeline decoupling the python producer
from the compiled step.

Capability parity with the reference in-graph reader stack (reference:
python/paddle/fluid/layers/io.py:449 `py_reader` + `read_file`;
paddle/fluid/operators/reader/lod_tensor_blocking_queue.h — bounded queue
fed from python, consumed by the executor's read op; EOF raises
core.EOFException).

TPU-native redesign: there is no in-graph read op — the jitted step takes
feeds as arguments — so the blocking queue sits at the feed boundary: a
producer thread converts batches (DataFeeder) and optionally pre-transfers
them to device, and `Executor.run(feed=None)` on a program bound to a
PyReader pops the next batch (raising EOFException at end-of-data, exactly
the reference's drain contract). The capacity bound gives backpressure; the
device pre-transfer gives the double_buffer H2D overlap."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, List, Optional, Sequence

import jax

from ..core import ir
from ..core.executor import EOFException
from ..data_feeder import DataFeeder
from ..layer_helper import LayerHelper

_EOF = object()


class PyReader:
    def __init__(self, feed_vars: List[ir.Variable], capacity: int,
                 program: Optional[ir.Program] = None,
                 use_double_buffer: bool = True):
        self.feed_vars = feed_vars
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._program = program or ir.default_main_program()
        self._program._py_reader = self
        self._feeder = DataFeeder(feed_list=feed_vars,
                                  program=self._program)
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event: Optional[threading.Event] = None
        self._producer_error: Optional[BaseException] = None
        self._batch_reader: Optional[Callable[[], Iterable]] = None
        self._tensor_provider: Optional[Callable[[], Iterable]] = None

    # -- binding (reference decorate_paddle_reader / decorate_tensor_provider)
    def decorate_paddle_reader(self, reader: Callable[[], Iterable]):
        """`reader()` yields BATCHES: lists of per-var sample tuples
        (compose with paddle_tpu.reader.batch)."""
        self._batch_reader = reader
        return self

    def decorate_tensor_provider(self, provider: Callable[[], Iterable]):
        """`provider()` yields ready feed dicts (or per-var array lists)."""
        self._tensor_provider = provider
        return self

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._batch_reader is None and self._tensor_provider is None:
            raise ValueError("bind a source first: decorate_paddle_reader "
                             "or decorate_tensor_provider")
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("py_reader already started; call reset() "
                               "after EOFException before restarting")
        self._queue = queue.Queue(maxsize=self.capacity)
        self._producer_error = None
        self._stop_event = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(self._queue, self._stop_event),
            daemon=True, name="py_reader")
        self._thread.start()

    def reset(self):
        """Drain after EOF — or abandon a mid-epoch producer (reference
        reader->reset per epoch). A still-running producer is signalled to
        stop so it cannot stay blocked on the abandoned queue pinning
        device-resident batches."""
        if self._stop_event is not None:
            self._stop_event.set()
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None
        self._queue = None
        self._producer_error = None

    def _produce(self, q, stop):
        def put(item):
            # bounded put that honours reset(): without the stop check a
            # producer abandoned mid-epoch would block on the full old
            # queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            if self._tensor_provider is not None:
                for item in self._tensor_provider():
                    feed = (item if isinstance(item, dict) else
                            {v.name: a for v, a in zip(self.feed_vars, item)})
                    if not put(self._maybe_transfer(feed)):
                        return
            else:
                for batch in self._batch_reader():
                    feed = self._feeder.feed(batch)
                    if not put(self._maybe_transfer(feed)):
                        return
        except BaseException as e:  # surfaced by next_feed, NOT silent EOF
            self._producer_error = e
        finally:
            put(_EOF)

    def _maybe_transfer(self, feed):
        if not self.use_double_buffer:
            return feed
        # pre-transfer dense arrays so the step's H2D overlaps prior compute
        out = {}
        for k, v in feed.items():
            if isinstance(v, tuple):
                out[k] = (jax.device_put(v[0]), v[1])
            else:
                out[k] = jax.device_put(v)
        return out

    # -- executor hook -----------------------------------------------------
    def next_feed(self):
        if self._queue is None:
            raise RuntimeError("py_reader not started — call reader.start()")
        item = self._queue.get()
        if item is _EOF:
            if self._producer_error is not None:
                err = self._producer_error
                raise RuntimeError(
                    "py_reader producer thread failed (this is NOT "
                    "end-of-data)") from err
            raise EOFException("py_reader drained (end of data pass)")
        return item

    def __iter__(self):
        """Also usable as a plain feed iterator."""
        while True:
            try:
                yield self.next_feed()
            except EOFException:
                return


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Declare feed vars + blocking-queue reader (reference io.py:449).
    Returns (reader, feed_vars) — the reference's read_file(reader) step is
    folded in because feeds are explicit here."""
    helper = LayerHelper("py_reader", name=name)
    lod_levels = lod_levels or [0] * len(shapes)
    feed_vars = []
    from ..layers import io as lio
    for i, (shape, dtype, lod) in enumerate(zip(shapes, dtypes, lod_levels)):
        v = lio.data(name=f"{helper.name}.slot{i}", shape=list(shape),
                     dtype=dtype, lod_level=lod, append_batch_size=False)
        feed_vars.append(v)
    reader = PyReader(feed_vars, capacity,
                      use_double_buffer=use_double_buffer)
    return reader, feed_vars
