"""Generic program-pass infrastructure.

Capability parity with the reference's graph/pass machinery (reference:
paddle/fluid/framework/ir/pass.h `Pass`/`PassRegistry`,
ir/graph.h `Graph`, ir/graph_viz_pass.cc). The reference rewrites an SSA
graph between build and execution; here passes rewrite the Program IR
before it is lowered into one XLA computation (XLA owns the
operator-fusion passes the reference's ir/ also hosted — see
docs/RETIREMENT.md).

Built-in passes wrap the existing transpilers, so the two reference
workflows converge:

    prog = apply_pass("fuse_batch_norm", prog, scope=scope)
    prog = apply_pass("memory_optimize", prog)
    apply_pass("graph_viz", prog, path="/tmp/prog.dot")
"""

from __future__ import annotations

from typing import Callable, Dict, List

from .core import ir

_REGISTRY: Dict[str, "Pass"] = {}


class Pass:
    """Base pass: override apply(program, **kw) -> program (reference
    Pass::Apply, ir/pass.h). Passes may mutate in place; they must return
    the program they leave valid."""

    name = "pass"
    mutates = True   # read-only passes set False to keep compiled caches

    def apply(self, program: ir.Program, **kwargs) -> ir.Program:
        raise NotImplementedError

    def __call__(self, program: ir.Program, **kwargs) -> ir.Program:
        out = self.apply(program, **kwargs)
        if out is None:
            out = program
        if self.mutates and hasattr(out, "_bump"):
            out._bump()   # invalidate compiled-step caches
        return out


def register_pass(name: str):
    """reference REGISTER_PASS macro analog."""
    def deco(cls):
        if name in _REGISTRY:
            raise ValueError(f"pass {name!r} already registered")
        inst = cls()
        inst.name = name
        _REGISTRY[name] = inst
        return cls
    return deco


def get_pass(name: str) -> Pass:
    if name not in _REGISTRY:
        raise KeyError(f"unknown pass {name!r}; registered: "
                       f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]


def registered_passes() -> List[str]:
    return sorted(_REGISTRY)


def apply_pass(name: str, program: ir.Program, **kwargs) -> ir.Program:
    return get_pass(name)(program, **kwargs)


# ---------------------------------------------------------------------------
# built-ins
# ---------------------------------------------------------------------------

@register_pass("graph_viz")
class GraphVizPass(Pass):
    """DOT dump of the global block (reference ir/graph_viz_pass.cc)."""

    mutates = False   # inspection only: a version bump here would force a
                      # full XLA recompile of the next training step

    def apply(self, program, path="/tmp/program.dot", **kw):
        from . import debugger
        debugger.draw_block_graphviz(program.global_block(), path=path)
        return program


@register_pass("memory_optimize")
class MemoryOptimizePass(Pass):
    """Rematerialization marks (reference memory_optimize transpiler)."""

    def apply(self, program, skip_opt_set=None, **kw):
        from .transpiler.memory_optimization_transpiler import memory_optimize
        memory_optimize(program, skip_opt_set=skip_opt_set)
        return program


@register_pass("fuse_batch_norm")
class FuseBatchNormPass(Pass):
    """Exact conv+BN fold for inference (reference
    inference_transpiler.py fuse_batch_norm :107)."""

    def apply(self, program, scope=None, place=None, **kw):
        from .transpiler.inference_transpiler import InferenceTranspiler
        t = InferenceTranspiler()
        t.transpile(program, place, scope=scope)
        return program


@register_pass("prune_for_inference")
class PruneForInferencePass(Pass):
    """Backward-slice to the given targets (reference prune.cc:181 via
    Program._prune)."""

    def apply(self, program, targets=None, **kw):
        if not targets:
            raise ValueError("prune_for_inference needs targets=[names]")
        names = [t.name if hasattr(t, "name") else str(t) for t in targets]
        return program._prune(names)


@register_pass("verify")
class VerifyPass(Pass):
    """Whole-program static verification (analysis/): structural checks +
    shape/dtype cross-check + TPU lints. Read-only by contract — it must
    never bump the program version (a bump would recompile the next step
    and invalidate prepared-executor handles for an inspection)."""

    mutates = False

    def apply(self, program, feed_targets=None, fetch_targets=None,
              raise_on_error=True, collect=None, lint=True, **kw):
        """`collect`: a caller-provided list the diagnostics are appended
        to (the pass API returns the program, not findings). With
        `raise_on_error` (default), ERROR findings raise
        ProgramVerificationError."""
        from . import analysis
        diags = analysis.analyze_program(
            program, feed_targets=feed_targets, fetch_targets=fetch_targets,
            lint=lint)
        if collect is not None:
            collect.extend(diags)
        if raise_on_error and analysis.has_errors(diags):
            raise analysis.ProgramVerificationError(diags)
        return program


@register_pass("infer_shapes")
class InferShapesPass(Pass):
    """Whole-program shape/dtype propagation with write-back: fills
    Variables whose build-time inference left an empty shape (reference:
    the block-wide InferShape sweep, shape_inference.h:30). Mutates
    declarations, so compiled caches are invalidated by the base-class
    version bump."""

    def apply(self, program, collect=None, **kw):
        from .analysis import infer_program_shapes
        _, diags = infer_program_shapes(program, update=True)
        if collect is not None:
            collect.extend(diags)
        return program
