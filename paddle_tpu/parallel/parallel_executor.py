"""ParallelExecutor: data-parallel training via GSPMD sharding.

Capability parity with the reference ParallelExecutor (reference:
paddle/fluid/framework/parallel_executor.cc:118-330 + details/ SSA graph,
python/paddle/fluid/parallel_executor.py).

TPU-native redesign: the reference replicates the program per GPU, builds an
SSA dependency graph, and hand-inserts NCCL AllReduce ops on gradients
(details/all_reduce_op_handle.cc:47). Here the SAME single-program lowering
used by Executor is compiled once under a `jax.sharding.Mesh`: feeds are
placed batch-sharded over the 'dp' axis, parameters replicated (kAllReduce
analog), and XLA GSPMD inserts the gradient all-reduces over ICI. The
`BuildStrategy.ReduceStrategy.Reduce` mode (sharded optimizer updates,
reference details/reduce_op_handle.cc) maps to sharding optimizer state over
'dp' — XLA then emits reduce-scatter + all-gather, the ZeRO-style pattern.
"""

from __future__ import annotations

import enum
import time
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core import ir
from ..core.executor import (Scope, _CompiledProgram, _StateCache,
                             _evict_stale_versions, _evict_superseded,
                             global_scope)
from ..observe import steplog as _steplog
from . import mesh as mesh_lib


class ExecutionStrategy:
    """Accepted for reference API parity (execution_strategy.h:21); XLA owns
    scheduling so only `num_threads` is meaningful (host callback pool)."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100


class BuildStrategy:
    class ReduceStrategy(enum.Enum):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(enum.Enum):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        # TPU extensions: name-pattern -> PartitionSpec for model parallelism,
        # and bf16 mixed precision for the MXU ops.
        self.sharding_rules = []
        self.amp = False
        # fluid-wire: "int8" / "bf16" inserts comm_quant_dequant ops with
        # persistent error feedback before every optimizer op
        # (wire/graph.py), quantizing each dp shard's gradient
        # contribution at the GSPMD all-reduce boundary — still ONE
        # jitted steady-state program (zero extra recompiles). None (the
        # default) keeps full-precision gradients.
        self.comm_quant = None


class ParallelExecutor:
    """Drop-in ParallelExecutor over a TPU mesh.

    `use_cuda` is accepted for reference parity and ignored. Feeds are split
    along the batch dim across the mesh 'dp' axis (the reference split feed
    lists per device in parallel_executor.py:run).
    """

    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, mesh: Optional[Mesh] = None,
                 use_tpu=True):
        self._program = main_program or ir.default_main_program()
        self._scope = scope or (share_vars_from._scope if share_vars_from
                                else global_scope())
        self._mesh = mesh or mesh_lib.get_default_mesh()
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._loss_name = loss_name
        self._cache: Dict[tuple, _CompiledProgram] = {}
        # prepared fast path (the Executor.prepare analog): memoizes the
        # full cache-key build + flag reads per (program version, feed
        # signature, fetch set, flag registry version), and caches the
        # O(params) scope state gather against the scope version counter
        self._fast: Dict[tuple, _CompiledProgram] = {}
        self._state_cache = _StateCache()
        self._last_key = None
        self._run_counter = 0
        self._replicated = NamedSharding(self._mesh, PartitionSpec())
        # fluid-wire: rewrite BEFORE the first compile/bcast — the
        # residual vars are materialized straight into this executor's
        # scope (the startup program typically already ran) and ride
        # _bcast_params onto the mesh like any other state
        if getattr(self._build_strategy, "comm_quant", None):
            from ..wire.graph import apply_comm_quant
            apply_comm_quant(self._program,
                             codec=self._build_strategy.comm_quant,
                             scope=self._scope)
        self._bcast_params()

    # reference BCastParamsToDevices (parallel_executor.cc:204): replicate
    # host/chip0 params across the mesh.
    def _bcast_params(self):
        sharding_for = self._sharding_for_state
        for name in list(self._scope.local_var_names()):
            val = self._scope.find_var(name)
            if val is None or not hasattr(val, "shape"):
                continue
            self._scope.set_var(name, self._place_global(
                val, sharding_for(name, val)))

    def _place_global(self, val, sharding):
        """Place a host-local value under `sharding`. Single-controller:
        plain device_put. Multi-host: device_put cannot target remote
        devices, so the global array is assembled from each process's
        local copy (every host initialized identical params from the same
        seeded startup program — the reference broadcasts from dev0
        instead, parallel_executor.cc:204)."""
        if jax.process_count() == 1:
            return jax.device_put(val, sharding)
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            # already a world-spanning array (multi-controller jit outputs
            # are): keep it if the sharding already matches, else localize
            if val.sharding == sharding:
                return val
            val = self._fetch_numpy(val)
        val = np.asarray(val)
        idx_map = sharding.addressable_devices_indices_map(val.shape)
        shards = [jax.device_put(val[idx], d) for d, idx in idx_map.items()]
        return jax.make_array_from_single_device_arrays(val.shape, sharding,
                                                        shards)

    def _sharding_for_state(self, name, val):
        # 1. Parameter-level annotations (ParamAttr.sharding, e.g. the
        #    transformer's Megatron-style 'mp' specs).
        var = self._program.global_block().vars.get(name)
        spec = getattr(var, "sharding", None)
        if spec:
            names = set(self._mesh.axis_names)
            spec = [s if (s in names) else None for s in spec]
            shape = getattr(val, "shape", ())
            ok = len(shape) == len(spec)
            if ok:
                for d, s in zip(shape, spec):
                    if s is not None and d % self._mesh.shape[s] != 0:
                        ok = False
            if ok and any(s is not None for s in spec):
                return NamedSharding(self._mesh, PartitionSpec(*spec))
        # 2. BuildStrategy pattern rules.
        for pattern, spec in self._build_strategy.sharding_rules:
            if pattern in name:
                return NamedSharding(self._mesh, PartitionSpec(*spec))
        if (self._build_strategy.reduce_strategy
                is BuildStrategy.ReduceStrategy.Reduce):
            # ZeRO-style: shard state along dim 0 over 'dp' when divisible.
            shape = getattr(val, "shape", ())
            ndev = self._mesh.devices.size
            if shape and shape[0] % ndev == 0 and shape[0] >= ndev:
                spec = [None] * len(shape)
                spec[0] = "dp"
                return NamedSharding(self._mesh, PartitionSpec(*spec))
        return self._replicated

    @property
    def device_count(self):
        return self._mesh.devices.size

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict or {}
        if isinstance(feed, (list, tuple)):
            merged: Dict[str, np.ndarray] = {}
            for d in feed:
                for k, v in d.items():
                    merged.setdefault(k, []).append(np.asarray(v))
            feed = {k: np.concatenate(v, axis=0) for k, v in merged.items()}

        from .. import flags as _flags
        obs_on = _flags.get_flag("observe")
        t0 = time.perf_counter() if obs_on else 0.0
        fetch_names = [f.name if isinstance(f, ir.Variable) else str(f)
                       for f in fetch_list]
        feed_arrays = self._convert_feeds(feed)
        if obs_on:
            t_fc = time.perf_counter()  # end of feed conversion proper

        fast_key = (self._program._uid, self._program._version,
                    frozenset(feed_arrays), tuple(fetch_names),
                    _flags.version())
        hit = self._fast.get(fast_key)
        bound = hit is None
        if hit is None:
            from ..core.executor import resolve_compiler_options
            copts = resolve_compiler_options(
                self._mesh.devices.flat[0].platform, self._program)
            key = (self._program._uid, self._program._version,
                   tuple(sorted(feed_arrays)), tuple(fetch_names),
                   _flags.get_flag("dropout_impl"),
                   tuple(sorted(copts.items())) if copts else None)
            compiled = self._cache.get(key)
            if compiled is None:
                _steplog.observatory().note_entry_build(
                    self._program._uid, self._program._version,
                    tuple(sorted(feed_arrays)), tuple(fetch_names),
                    tuple(sorted(copts.items())) if copts else None,
                    source="parallel", scope_uid=self._scope._uid)
                compiled = _CompiledProgram(self._program, sorted(feed_arrays),
                                            fetch_names, self._scope,
                                            donate=True,
                                            amp=self._build_strategy.amp,
                                            mesh=self._mesh,
                                            compiler_options=copts)
                _evict_stale_versions(self._cache, self._program._uid,
                                      self._program._version)
                self._cache[key] = compiled
            _evict_stale_versions(self._fast, self._program._uid,
                                  self._program._version)
            # a flag flip re-keys the memo for the same (program, feed
            # signature, fetch set) — drop the superseded entry
            _evict_superseded(self._fast, fast_key)
            hit = self._fast[fast_key] = (compiled, key)
        compiled, self._last_key = hit

        if obs_on:
            _steplog.track_shapes(compiled, self._program._uid, feed_arrays,
                                  source="parallel")
            t1 = time.perf_counter()
        # per-program run counter (see Executor.run): deterministic
        # trajectories from seeded init, per-step mask variation
        counter = np.uint32(self._run_counter)
        self._run_counter += 1
        mut, const = self._state_cache.get(compiled, self._scope)
        if obs_on:
            t2 = time.perf_counter()
        fetches, new_state = compiled.run_with_state(
            self._scope, feed_arrays, mut, const, counter)
        if obs_on:
            t3 = time.perf_counter()
        self._state_cache.commit(compiled, self._scope, new_state)
        if obs_on:
            t4 = time.perf_counter()
        if return_numpy:
            fetches = [self._fetch_numpy(f) for f in fetches]
        if obs_on:
            t5 = time.perf_counter()
            phases = {
                "feed_convert": t_fc - t0,
                "state_gather": t2 - t1,
                "device_compute": t3 - t2,
                "write_back": t4 - t3,
                "fetch": t5 - t4,
            }
            if bound:
                # one-shot memo-resolution/build cost, kept out of the
                # steady-state feed_convert numbers
                phases["bind"] = t1 - t_fc
            _steplog.get_steplog().record(_steplog.StepStats(
                self._program._uid, "parallel", time.time(), phases))
        return fetches

    @staticmethod
    def _fetch_numpy(f):
        """Multi-host fetch: a global array spanning remote devices cannot
        be np.asarray'd directly — read the local copy when replicated,
        allgather otherwise (every process calls fetch symmetrically, so
        the collective is safe)."""
        if isinstance(f, jax.Array) and not f.is_fully_addressable:
            if f.sharding.is_fully_replicated:
                return np.asarray(f.addressable_shards[0].data)
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(f,
                                                                tiled=True))
        return np.asarray(f)

    def _convert_feeds(self, feed):
        block = self._program.global_block()
        feed_arrays = {}
        for name, val in feed.items():
            var = block.vars.get(name)
            if isinstance(val, (tuple, list)) and len(val) == 2 and var is not None \
                    and var.lod_level > 0:
                data, lens = val
                feed_arrays[name] = self._shard_feed(data, var)
                if isinstance(lens, (tuple, list)) and len(lens) == 2 \
                        and not np.isscalar(lens[0]):
                    # nested LoD: (outer counts [B], inner lengths [B, S])
                    feed_arrays[ir.seqlen_var_name(name)] = self._shard_feed(
                        np.asarray(lens[0], np.int32), var)
                    feed_arrays[ir.seqlen_var_name(name, 1)] = \
                        self._shard_feed(np.asarray(lens[1], np.int32), var)
                else:
                    feed_arrays[ir.seqlen_var_name(name)] = self._shard_feed(
                        np.asarray(lens, np.int32), var)
            else:
                feed_arrays[name] = self._shard_feed(val, var)
        return feed_arrays

    def lowered_text(self, feed) -> str:
        """StableHLO text of the step this feed shape ran through — the
        supported way to inspect what GSPMD emitted (tests/dryrun assert
        on collective ops here instead of poking privates). Requires a
        prior run() with the same feed names (and fetch list)."""
        if not self._cache:
            raise RuntimeError("lowered_text requires a prior run()")
        feeds = self._convert_feeds(feed)
        names = tuple(sorted(feeds))
        cands = [k for k in self._cache
                 if k[2] == names and k[1] == self._program._version]
        if not cands:
            raise RuntimeError(
                f"no compiled step matches feed names {sorted(feeds)}; "
                f"run() with this feed first")
        # prefer the step the LAST run used (disambiguates fetch lists)
        key = self._last_key if self._last_key in cands else cands[-1]
        compiled = self._cache[key]
        mut = {n: self._scope.find_var(n) for n in compiled.mut_names}
        const = {n: self._scope.find_var(n) for n in compiled.const_names}
        return compiled._step.lower({k: feeds[k] for k in sorted(feeds)},
                                    mut, const, np.uint32(0)).as_text()

    def compiled_text(self, feed) -> str:
        """Optimized-HLO text of the compiled step — AFTER GSPMD
        partitioning, so the collectives XLA actually inserted
        (all-reduce / all-gather / collective-permute / reduce-scatter)
        are visible and countable. Same contract as lowered_text: run()
        with this feed first."""
        if not self._cache:
            raise RuntimeError("compiled_text requires a prior run()")
        feeds = self._convert_feeds(feed)
        names = tuple(sorted(feeds))
        cands = [k for k in self._cache
                 if k[2] == names and k[1] == self._program._version]
        if not cands:
            raise RuntimeError(
                f"no compiled step matches feed names {sorted(feeds)}; "
                f"run() with this feed first")
        key = self._last_key if self._last_key in cands else cands[-1]
        compiled = self._cache[key]
        # memoize: the AOT compile below is a second full GSPMD+XLA
        # compile of a step run() already compiled (the jit-internal
        # executable is not publicly reachable); callers probing the
        # inventory repeatedly must not pay it repeatedly
        if getattr(compiled, "_hlo_text", None) is not None:
            return compiled._hlo_text
        mut = {n: self._scope.find_var(n) for n in compiled.mut_names}
        const = {n: self._scope.find_var(n) for n in compiled.const_names}
        compiled._hlo_text = (
            compiled._step.lower({k: feeds[k] for k in sorted(feeds)},
                                 mut, const, np.uint32(0))
            .compile().as_text())
        return compiled._hlo_text

    def _shard_feed(self, arr, var=None):
        # already-global arrays (dist.shard_local_batch on multi-host, or a
        # re-fed fetch) pass through untouched
        if isinstance(arr, jax.Array) and getattr(arr, "sharding", None) is not None \
                and isinstance(arr.sharding, NamedSharding) \
                and arr.sharding.mesh == self._mesh:
            return arr
        arr = np.asarray(arr)
        if arr.ndim == 0:
            return self._place_global(arr, self._replicated)
        dp = self._mesh.shape.get("dp", 1)  # no 'dp' axis -> replicated dim 0
        if arr.shape[0] % dp != 0:
            if var is None or var.is_data:
                # a silently replicated DATA feed would train every device
                # on the SAME rows — a correctness bug, not a fallback
                # (reference PE enforces divisibility via data_balance)
                raise ValueError(
                    f"feed batch dim {arr.shape[0]} is not divisible by the "
                    f"{dp}-way data-parallel mesh axis; pad or drop the "
                    f"tail batch (reader.batch(..., drop_last=True))")
            # non-data feeds (lr schedules, class weights, ...) have no
            # batch dimension — replicate
            return self._place_global(arr, self._replicated)
        spec = [None] * arr.ndim
        spec[0] = "dp" if "dp" in self._mesh.axis_names else None
        # sequence parallelism: shard the seq dim of data feeds over 'sp'
        # so ring attention's Q/K/V shards arrive pre-placed
        if ("sp" in self._mesh.axis_names and arr.ndim >= 2
                and var is not None and var.is_data
                and arr.shape[1] % self._mesh.shape["sp"] == 0):
            spec[1] = "sp"
        return self._place_global(arr, NamedSharding(self._mesh,
                                                     PartitionSpec(*spec)))


def collective_inventory(hlo_text: str) -> dict:
    """Count the collective ops in an optimized-HLO module (one compiled
    step): which collectives GSPMD actually inserted for a mesh, per
    step. Async pairs (`-start`/`-done`) count once."""
    inv = {}
    for kind in ("all-reduce", "all-gather", "collective-permute",
                 "reduce-scatter", "all-to-all"):
        n = hlo_text.count(f" {kind}(") + hlo_text.count(f" {kind}-start(")
        if n:
            inv[kind] = n
    return inv
