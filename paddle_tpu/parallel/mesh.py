"""Device mesh helpers.

The reference enumerates CUDAPlaces and builds NCCL communicators per device
(reference: platform/nccl_helper.h:81 NCCLContextMap). TPU-native: a
`jax.sharding.Mesh` over all local (or all distributed) devices; axes are
named so programs can shard over data ('dp'), model ('mp'/'tp'), pipeline
('pp'), and sequence ('sp') dimensions.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(axis_sizes)
    return Mesh(arr, tuple(axis_names))


def get_default_mesh(num_devices: Optional[int] = None) -> Mesh:
    """1-D data-parallel mesh over all devices (ParallelExecutor default)."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh([len(devices)], ["dp"], devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis))
