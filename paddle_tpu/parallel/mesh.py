"""Device mesh helpers.

The reference enumerates CUDAPlaces and builds NCCL communicators per device
(reference: platform/nccl_helper.h:81 NCCLContextMap). TPU-native: a
`jax.sharding.Mesh` over all local (or all distributed) devices; axes are
named so programs can shard over data ('dp'), model ('mp'/'tp'), pipeline
('pp'), and sequence ('sp') dimensions.

fluid-planner: `auto_mesh(program, n_devices)` derives the dp×mp×sp
split from the program's cost model instead of a hand-picked tuple —
see `analysis.planner.plan_meshes` and docs/PLANNER.md.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(axis_sizes)
    return Mesh(arr, tuple(axis_names))


def get_default_mesh(num_devices: Optional[int] = None) -> Mesh:
    """1-D data-parallel mesh over all devices (ParallelExecutor default)."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh([len(devices)], ["dp"], devices)


def auto_mesh(program, n_devices: Optional[int] = None,
              feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
              devices=None, hw=None, default_batch: int = 8,
              return_report: bool = False):
    """Cost-model-driven mesh selection (fluid-planner): search the
    dp×mp×sp factorizations of `n_devices` for `program` and build the
    Mesh of the fastest-predicted feasible candidate. Callers that used
    to hand-tune `make_mesh([dp, mp, sp], ...)` can drop the tuple:

        mesh = auto_mesh(main_program, n_devices=8)
        pe = ParallelExecutor(main_program=main, loss_name=loss.name,
                              mesh=mesh, scope=scope)

    `feed_shapes` sizes the batch/sequence extents the feasibility and
    cost models use; when omitted, the program's data vars are read with
    any -1 batch dim resolved to `default_batch`. `hw` is an
    `analysis.planner.HardwareSpec` (default: detected from the jax
    backend — the calibrated chip profile on TPU, the virtual-device
    rehearsal profile on CPU). `return_report=True` also returns the
    ranked `PlanReport` (predicted step time / MFU / peak HBM /
    bytes-on-the-wire per candidate). Raises ValueError when no
    candidate is feasible, naming each rejection."""
    from ..analysis import planner as _planner

    devices = list(devices) if devices is not None else jax.devices()
    n = int(n_devices) if n_devices is not None else len(devices)
    if feed_shapes is None:
        # only the BATCH dim may be defaulted: a non-batch -1 (dynamic
        # sequence/spatial axis) has no sane default, and planning sp
        # feasibility or ring-attention cost at a made-up extent would
        # silently mis-rank the mesh — the caller must say what the
        # real workload looks like
        feed_shapes = {}
        for v in program.global_block().vars.values():
            if not getattr(v, "is_data", False) or v.shape == ():
                continue
            shape = [int(d) for d in v.shape]
            if any(d == -1 for d in shape[1:]):
                raise ValueError(
                    f"auto_mesh: data var {v.name!r} has a dynamic "
                    f"non-batch dim {tuple(shape)} — pass feed_shapes= "
                    f"with the concrete extents the workload will run")
            if shape and shape[0] == -1:
                shape[0] = int(default_batch)
            feed_shapes[v.name] = tuple(shape)
    report = _planner.plan_meshes(program, feed_shapes, n, hw=hw)
    best = report.best
    if best is None:
        reasons = "; ".join(f"{c.label()}: {c.reason}"
                            for c in report.candidates)
        raise ValueError(
            f"auto_mesh: no feasible dp*mp*sp split of {n} device(s) "
            f"for this program — {reasons}")
    mesh = make_mesh([best.dp, best.mp, best.sp], ["dp", "mp", "sp"],
                     devices[:n])
    return (mesh, report) if return_report else mesh


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(axis))
