"""Parallel execution over TPU meshes (GSPMD/pjit).

Replaces the reference's multi-device machinery (SSA-graph ParallelExecutor +
NCCL, reference paddle/fluid/framework/details/) with sharding annotations
over a `jax.sharding.Mesh`: XLA GSPMD inserts the collectives (psum /
all-gather / reduce-scatter) that the reference issued by hand.
"""

from .parallel_executor import ParallelExecutor, BuildStrategy, ExecutionStrategy  # noqa: F401
from .mesh import auto_mesh, get_default_mesh, make_mesh  # noqa: F401
