"""Python side of the C inference ABI (paddle_tpu/capi/).

The C++ shim (capi.cc) embeds CPython and calls `create` / `Predictor.run`
here; this module owns the model, scope and the jit-compiled step —
exactly the path `Inferencer` uses, so the C ABI and the Python API share
one predictor implementation (reference analog: api_impl.cc
NativePaddlePredictor::Run driving the same Executor as python).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

# JAX_PLATFORMS=cpu is honored by the paddle_tpu package __init__ (which
# importing this module executes first): a host asking for a CPU
# predictor never silently routes through an accelerator tunnel.


class Predictor:
    def __init__(self, model_dir: str):
        import paddle_tpu as fluid
        self._fluid = fluid
        self.scope = fluid.Scope()
        self.exe = fluid.Executor(fluid.TPUPlace(0))
        self.program, self.feed_names, self.fetch_targets = \
            fluid.io.load_inference_model(model_dir, self.exe,
                                          scope=self.scope)

    def run(self, feed_list: List[Tuple[str, tuple, str, bytes]]):
        """feed_list entries: (name, shape, dtype_str, raw_bytes); empty
        name means positional (feed_names order). Returns a list of
        (fetch_name, dtype_str, contiguous ndarray)."""
        feeds = {}
        for i, (name, shape, dtype, raw) in enumerate(feed_list):
            arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
            feeds[name or self.feed_names[i]] = arr
        outs = self.exe.run(self.program, feed=feeds,
                            fetch_list=self.fetch_targets, scope=self.scope)
        results = []
        for tgt, v in zip(self.fetch_targets, outs):
            a = np.ascontiguousarray(np.asarray(v))
            if a.dtype == np.float64:
                a = a.astype(np.float32)
            name = tgt.name if hasattr(tgt, "name") else str(tgt)
            results.append((name, str(a.dtype), a))
        return results


def create(model_dir: str) -> Predictor:
    return Predictor(model_dir)
