"""Parameter initializers appended as startup-program ops.

Capability parity with reference python/paddle/fluid/initializer.py:
Constant/Uniform/Normal/TruncatedNormal/Xavier/MSRA/Bilinear. Each initializer
appends one op to the startup program; on TPU all init ops compile into a
single XLA computation run once (reference runs them per-op on first
executor run).
"""

from __future__ import annotations

import math

import numpy as np


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": float(self.low), "max": float(self.high),
                               "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc), "std": float(self.scale),
                               "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc), "std": float(self.scale),
                               "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        receptive = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
    else:
        fan_in = fan_out = int(np.prod(shape))
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For upsampling conv_transpose weights (reference initializer.py)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("bilinear init needs a 4-D weight")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = shape[2] * shape[3]
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight.flat[i] = w if (i // size) % shape[1] == (i // size // shape[1]) % shape[0] else 0
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(shape), "dtype": var.dtype,
                               "values": [float(v) for v in weight.reshape(-1)]})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(self.value.shape), "dtype": var.dtype,
                               "values": [float(v) for v in self.value.reshape(-1)]})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)


# -- CPU-pinning knobs (reference initializer.py force_init_on_cpu /
# init_on_cpu). The reference pinned initializer ops to CPU to dodge GPU
# RNG divergence; on TPU startup programs are one deterministic XLA
# computation keyed on the program seed, so the knob is semantically a
# no-op — the API is kept for source compatibility.

import contextlib as _contextlib

_force_init_on_cpu = False


def force_init_on_cpu():
    return _force_init_on_cpu


@_contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu
    prev = _force_init_on_cpu
    _force_init_on_cpu = True
    try:
        yield
    finally:
        _force_init_on_cpu = prev
