"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py).

Appended per-parameter to the gradient before the update op, exactly as the
reference does (`append_regularization_ops`)."""

from __future__ import annotations

from .core import ir


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(
            name=f"{param.name}@l2decay_{len(block.ops)}",
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("scale", inputs={"X": [param.name]},
                        outputs={"Out": [decay.name]},
                        attrs={"scale": self._coeff})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(
            name=f"{param.name}@l1sign_{len(block.ops)}",
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("sign", inputs={"X": [param.name]},
                        outputs={"Out": [sign.name]})
        decay = block.create_var(
            name=f"{param.name}@l1decay_{len(block.ops)}",
            shape=param.shape, dtype=param.dtype, stop_gradient=True)
        block.append_op("scale", inputs={"X": [sign.name]},
                        outputs={"Out": [decay.name]},
                        attrs={"scale": self._coeff})
        return decay


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Add decay terms onto each gradient (reference regularizer.py:24)."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularizer = param.regularizer or regularization
        if regularizer is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        decay = regularizer(param, grad, block)
        new_grad = block.create_var(
            name=f"{grad.name}@reg_{len(block.ops)}",
            shape=grad.shape, dtype=grad.dtype, stop_gradient=True)
        block.append_op("sum", inputs={"X": [grad.name, decay.name]},
                        outputs={"Out": [new_grad.name]})
        params_and_grads.append((param, new_grad))
    return params_and_grads
