"""One arbiter node of the fluid-quorum majority-lease service.

A node is deliberately tiny: per-resource volatile lease records
(holder, epoch, expiry on the node's monotonic clock) plus ONE durable
fact — the highest epoch this node ever granted, per resource. The
durable fact is what makes elections fenceable across arbiter crashes:

- a node grants a campaign only at an epoch STRICTLY above its
  persisted maximum, and persists the new maximum BEFORE replying, so a
  reply implies durability (`ark.atomic_file`: tmp + `os.replace` +
  fsync, with a sha256 sidecar so bit rot is refused loudly instead of
  silently restarting the node at epoch 0);
- each node grants each epoch at most once, so two concurrent campaigns
  for one resource can never BOTH collect a strict majority at the same
  epoch — node grants partition the group, and only one side can hold
  more than half;
- a restarted node has lost its volatile lease records, so it observes
  a **boot blackout**: campaigns are refused until the longest lease it
  might have granted before the crash has provably expired (the granted
  `lease_s` is persisted next to the epoch). Renewals at exactly the
  persisted epoch stay allowed through the blackout — the holder of the
  newest promise is re-asserting a lease this node already granted, and
  accepting it re-establishes the record instead of leaving the
  restarted node an easy vote for a rival.

Transport: the pserver RPC framing (`pserver/rpc.py`) — length-prefixed
restricted pickles, the same fault-hook seam `ark.chaos` injects into,
so a drill partitions arbiters with the identical machinery it uses on
pservers. Connection threads are named `qconn@<endpoint>` (the chaos
actor convention: the trailing `@<endpoint>` identifies the sender).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..ark import checkpoint as ark_ckpt

logger = logging.getLogger(__name__)

EPOCH_FILE = "quorum_epochs.json"


class QuorumStore:
    """The durable half of a node: resource -> (max granted epoch, the
    lease_s granted with it). Every mutation commits via the ark atomic
    idiom before the caller may act on it."""

    def __init__(self, data_dir: str, node_id: str):
        self.path = os.path.join(data_dir, f"{node_id}_{EPOCH_FILE}")
        self._lock = threading.Lock()
        self._epochs: Dict[str, Dict] = {}  # guarded_by: self._lock
        self._load()

    @staticmethod
    def _payload_sha(epochs: Dict[str, Dict]) -> str:
        import hashlib
        canon = json.dumps(epochs, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(canon.encode()).hexdigest()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        # checksum gate BEFORE trusting: a bit-rotted epoch file silently
        # parsed as {} would restart this node at epoch 0 — the one
        # regression the whole design exists to prevent. The checksum is
        # EMBEDDED in the same atomically-replaced file (a crash cannot
        # tear it: os.replace commits payload + sha as one unit, and a
        # grant whose persist never committed was never acknowledged);
        # the external sidecar is advisory operator tooling — written as
        # a second step, it CAN go stale across a crash between the
        # replace and the sidecar write, so a stale sidecar over a
        # self-verifying payload is healed, not fatal.
        try:
            with open(self.path) as f:
                raw = json.load(f)
        except ValueError as e:
            raise ark_ckpt.CheckpointError(
                f"{self.path} is not parseable ({e}) — refusing to "
                f"restart this arbiter at a regressed epoch") from e
        if isinstance(raw, dict) and "epochs" in raw and "sha256" in raw:
            if self._payload_sha(raw["epochs"]) != raw["sha256"]:
                raise ark_ckpt.CheckpointError(
                    f"{self.path} fails its embedded checksum — bit rot; "
                    f"refusing to restart this arbiter at a regressed "
                    f"epoch")
            self._epochs = {r: dict(rec)  # race_lint: ignore[unguarded-write] — __init__-only load path, pre-publication
                            for r, rec in raw["epochs"].items()}
            try:
                ark_ckpt.verify_sidecar(self.path)
            except ark_ckpt.CheckpointError:
                ark_ckpt.write_sidecar_manifest(self.path,
                                                kind="quorum_epochs")
        else:
            # legacy flat-mapping format: the sidecar is the only
            # verifier
            ark_ckpt.verify_sidecar(self.path)
            self._epochs = {r: dict(rec) for r, rec in raw.items()}  # race_lint: ignore[unguarded-write] — __init__-only load path, pre-publication

    def _commit_locked(self) -> None:
        doc = {"sha256": self._payload_sha(self._epochs),
               "epochs": self._epochs}
        with ark_ckpt.atomic_file(self.path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        ark_ckpt.write_sidecar_manifest(self.path, kind="quorum_epochs")

    def epoch(self, resource: str) -> int:
        with self._lock:
            return int(self._epochs.get(resource, {}).get("epoch", 0))

    def lease_s(self, resource: str) -> float:
        with self._lock:
            return float(self._epochs.get(resource, {}).get("lease_s", 0.0))

    def advance(self, resource: str, epoch: int, lease_s: float) -> None:
        """Persist a new maximum BEFORE the grant reply leaves."""
        with self._lock:
            cur = self._epochs.get(resource, {})
            if epoch <= int(cur.get("epoch", 0)):
                raise ValueError(
                    f"epoch must advance: {epoch} <= {cur.get('epoch', 0)}")
            self._epochs[resource] = {
                "epoch": int(epoch),
                "lease_s": max(float(lease_s), float(cur.get("lease_s",
                                                             0.0)))}
            self._commit_locked()

    def resources(self):
        with self._lock:
            return sorted(self._epochs)


class _Lease:
    __slots__ = ("holder", "epoch", "expires", "lease_s")

    def __init__(self, holder: str, epoch: int, lease_s: float):
        self.holder = holder
        self.epoch = int(epoch)
        self.lease_s = float(lease_s)
        self.expires = time.monotonic() + float(lease_s)

    @property
    def live(self) -> bool:
        return time.monotonic() < self.expires

    def renew(self, lease_s: float) -> None:
        self.lease_s = float(lease_s)
        self.expires = time.monotonic() + float(lease_s)


class QuorumNode:
    """One arbiter. `endpoint` may use port 0 (resolved after
    `start()`); `data_dir` holds the persisted epoch file. Thread-based
    like `ParameterServer`, so tests and drills run a 3/5-node group
    in-process where the chaos fault hook can reach every message."""

    def __init__(self, endpoint: str, data_dir: str,
                 node_id: Optional[str] = None):
        import uuid

        from ..pserver import rpc
        self._rpc = rpc
        self.endpoint = endpoint
        # the node id keys the persisted epoch file, so it must be
        # UNIQUE per node within a data_dir: an ephemeral endpoint
        # (":0") cannot name one before bind — every such node would
        # share "q0" and clobber each other's persisted maxima, the
        # exact regression the file prevents. Port-0 nodes therefore
        # get a fresh identity per process; pass node_id explicitly
        # whenever a RESTART must find the same epoch file (tests and
        # tools/quorum_node.py do).
        port = endpoint.rsplit(":", 1)[-1]
        self.node_id = node_id or (f"q{port}" if port != "0"
                                   else f"q0-{uuid.uuid4().hex[:8]}")
        os.makedirs(data_dir, exist_ok=True)
        self.store = QuorumStore(data_dir, self.node_id)
        self._leases: Dict[str, _Lease] = {}  # guarded_by: self._lock
        self._lock = threading.Lock()
        # boot blackout, PER RESOURCE: campaigns for a resource are
        # refused until the longest lease this node had granted on it
        # BEFORE this boot has provably expired (the volatile record
        # died with the old process). Snapshotted at boot: a resource
        # first granted AFTER boot has a live in-memory record and
        # needs no blackout, and one this node never granted (lease_s
        # 0) boots instantly — a restarted arbiter must not block the
        # bootstrap of brand-new shards.
        self._boot_at = time.monotonic()
        self._boot_lease_s = {r: self.store.lease_s(r)
                              for r in self.store.resources()}
        self._listener: Optional[socket.socket] = None
        self._conns: set = set()              # guarded_by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "QuorumNode":
        host, port = self._rpc.parse_endpoint(self.endpoint)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        if port == 0:
            self.endpoint = f"{host}:{self._listener.getsockname()[1]}"
        self._listener.listen(32)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"quorum@{self.endpoint}").start()
        logger.info("quorum node %s listening on %s (boot blackout "
                    "up to %.1fs per pre-boot resource)", self.node_id,
                    self.endpoint,
                    max(self._boot_lease_s.values(), default=0.0))
        return self

    def _blackout_remaining(self, resource: str) -> float:
        return (self._boot_at + self._boot_lease_s.get(resource, 0.0)
                - time.monotonic())

    def stop(self) -> None:
        """Hard cut, like `ParameterServer.stop()`: the listener and
        every live connection die now, in-flight requests unanswered."""
        self._stop.set()
        if self._listener is not None:
            for f in ("shutdown", "close"):
                try:
                    (self._listener.shutdown(socket.SHUT_RDWR)
                     if f == "shutdown" else self._listener.close())
                except OSError:
                    pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            for f in ("shutdown", "close"):
                try:
                    (c.shutdown(socket.SHUT_RDWR) if f == "shutdown"
                     else c.close())
                except OSError:
                    pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"qconn@{self.endpoint}").start()

    def _serve_conn(self, conn) -> None:
        rpc = self._rpc
        try:
            while not self._stop.is_set():
                try:
                    msg = rpc.recv_msg(conn)
                except (ConnectionError, EOFError, OSError):
                    return
                if self._stop.is_set():
                    return   # a stopped node behaves like a dead process
                try:
                    cmd, payload = msg[0], msg[1]
                except (TypeError, IndexError):
                    rpc.send_msg(conn, ("err", "MalformedFrame"))
                    continue
                try:
                    handler = getattr(self, f"_h_{cmd}", None)
                    if handler is None:
                        raise ValueError(f"unknown quorum command {cmd!r}")
                    reply = handler(**payload)
                except Exception as e:   # surface to the client
                    reply = ("err", f"{type(e).__name__}: {e}")
                rpc.send_msg(conn, reply)
                if cmd == "stop":
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    # -- handlers ---------------------------------------------------------
    def _h_q_hello(self):
        return ("ok", {"node_id": self.node_id, "endpoint": self.endpoint,
                       "version": 1})

    def _h_q_epoch(self, resource):
        return ("ok", {"epoch": self.store.epoch(resource)})

    def _h_q_campaign(self, resource, candidate, epoch, lease_s):
        """Grant `candidate` the lease on `resource` at exactly `epoch`,
        iff (a) the epoch strictly exceeds every epoch this node ever
        granted, (b) no OTHER holder's lease is currently live here, and
        (c) the node is past its boot blackout. Re-granting the SAME
        (candidate, epoch) is acknowledged idempotently — a retried
        campaign RPC whose first reply was lost must not read as a
        rejection."""
        epoch, lease_s = int(epoch), float(lease_s)
        with self._lock:
            cur_max = self.store.epoch(resource)
            rec = self._leases.get(resource)
            if rec is not None and rec.epoch == epoch \
                    and rec.holder == candidate and epoch == cur_max:
                rec.renew(lease_s)   # idempotent re-grant (lost reply)
                return ("ok", {"granted": True, "epoch": epoch,
                               "node_id": self.node_id})
            if epoch <= cur_max:
                return ("ok", {"granted": False, "reason": "stale_epoch",
                               "epoch": cur_max, "node_id": self.node_id})
            if rec is not None and rec.live and rec.holder != candidate:
                return ("ok", {"granted": False, "reason": "held",
                               "epoch": cur_max, "holder": rec.holder,
                               "expires_in_s": round(
                                   rec.expires - time.monotonic(), 3),
                               "node_id": self.node_id})
            remaining = self._blackout_remaining(resource)
            if remaining > 0 and (rec is None or rec.holder != candidate):
                # restarted node: a lease it granted on THIS resource
                # before the crash may still be live somewhere — refuse
                # to be an easy vote until it provably expired
                return ("ok", {"granted": False, "reason": "boot_blackout",
                               "epoch": cur_max,
                               "retry_in_s": round(remaining, 3),
                               "node_id": self.node_id})
            # durability BEFORE the reply: a crash between these two
            # statements loses the grant (candidate counts a missing
            # vote) but can never regress the promise
            self.store.advance(resource, epoch, lease_s)
            self._leases[resource] = _Lease(candidate, epoch, lease_s)
            return ("ok", {"granted": True, "epoch": epoch,
                           "node_id": self.node_id})

    def _h_q_renew(self, resource, holder, epoch, lease_s):
        """Refresh the lease iff `epoch` is still the newest this node
        promised AND no rival holds a live record. A restarted node with
        no volatile record accepts a renew at exactly its persisted
        epoch — the holder is re-asserting a promise this node made."""
        epoch, lease_s = int(epoch), float(lease_s)
        with self._lock:
            cur_max = self.store.epoch(resource)
            if epoch < cur_max:
                return ("ok", {"renewed": False, "reason": "fenced",
                               "epoch": cur_max, "node_id": self.node_id})
            if epoch > cur_max:
                # a holder claiming an epoch this node never granted: it
                # won elsewhere; re-establish durability here first so
                # this node can never later grant that epoch to a rival
                self.store.advance(resource, epoch, lease_s)
            rec = self._leases.get(resource)
            if rec is not None and rec.live and rec.holder != holder \
                    and rec.epoch >= epoch:
                return ("ok", {"renewed": False, "reason": "held",
                               "epoch": rec.epoch, "holder": rec.holder,
                               "node_id": self.node_id})
            if rec is None or rec.holder != holder or rec.epoch != epoch:
                self._leases[resource] = _Lease(holder, epoch, lease_s)
            else:
                rec.renew(lease_s)
            return ("ok", {"renewed": True, "epoch": epoch,
                           "node_id": self.node_id})

    def _h_q_resign(self, resource, holder, epoch):
        """Clear the volatile record iff it matches; the persisted epoch
        never regresses. Idempotent."""
        with self._lock:
            rec = self._leases.get(resource)
            if rec is not None and rec.holder == holder \
                    and rec.epoch == int(epoch):
                del self._leases[resource]
                return ("ok", {"resigned": True, "node_id": self.node_id})
        return ("ok", {"resigned": False, "node_id": self.node_id})

    def _h_q_status(self, resource):
        with self._lock:
            rec = self._leases.get(resource)
            out = {"epoch": self.store.epoch(resource),
                   "node_id": self.node_id,
                   "holder": rec.holder if rec else None,
                   "lease_epoch": rec.epoch if rec else 0,
                   "live": bool(rec and rec.live),
                   "expires_in_s": round(rec.expires - time.monotonic(), 3)
                   if rec else 0.0}
        return ("ok", out)

    def _h_stop(self):
        self.stop()
        return ("ok", None)
