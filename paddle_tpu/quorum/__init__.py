"""fluid-quorum: a partition-safe coordination plane.

fluid-haven (round 17) documented its own limit: a 2-node
primary/backup pair cannot tell "peer died" from "peer unreachable",
so lease-expiry auto-promotion had to stay off on partition-risky
networks — availability traded for safety. The reference repo parked
exactly this problem on etcd (its Go EDL master/pserver lean on etcd
leases for election and liveness); the TF system paper makes the same
move. fluid-quorum is that layer, TPU-runtime-native: a small
majority-lease arbiter riding the existing pserver RPC framing.

- **`QuorumNode`** (`node.py`): one arbiter process/thread holding
  per-resource lease records and a PERSISTED monotone fencing epoch
  (ark atomic-checkpoint idiom: tmp + `os.replace` + sha256 sidecar),
  so an arbiter restart can never regress an epoch it promised. A
  freshly restarted node also refuses new campaigns until any lease it
  might have granted before the crash has provably expired (the boot
  blackout) — losing the volatile lease table cannot mint two holders.

- **`QuorumClient`** (`client.py`): `campaign(resource)` / `renew` /
  `resign` against a 3- or 5-node arbiter group. A lease is HELD only
  with acks from a strict majority of nodes, every grant carries the
  fencing epoch (strictly above every epoch any majority ever granted),
  and a renew that cannot reach a majority FAILS CLOSED — the holder
  must stop accepting writes before the arbiters' lease expiry lets a
  rival win.

- **haven integration** (`haven/replication.py`): with a quorum
  configured, the standby promotes only on a quorum-granted lease and
  the primary self-fences when it cannot renew — `auto_promote=True`
  becomes the safe default under asymmetric partitions, and a deposed
  primary that still holds trainer sockets is fenced by epoch.

- **membership backing** (`ark/liveness.py::QuorumLeaseTable`,
  `ark/heartbeat.py`): an opt-in second liveness opinion for lease
  tables (fleet routers, pserver trainer leases) — a member that lost
  its path to the table owner but still renews at the arbiters is not
  falsely evicted. Without a quorum configured, every lease table
  behaves exactly as before.

See docs/FAULT_TOLERANCE.md §Quorum arbiter for the protocol, the
failure-model upgrade (crash-stop -> partition-tolerant), and the
3-vs-5-node sizing guidance; `ark/chaos.py::NetPartition` +
`tools/chaos_drill.py --scenario ps_partition` prove the claims.
"""

from .client import (EPOCH_METRIC, GRANTS_METRIC,  # noqa: F401
                     LEASE_OK_METRIC, MAJORITY_METRIC, UNREACHABLE_METRIC,
                     QuorumClient, QuorumLease, QuorumUnavailable)
from .node import QuorumNode, QuorumStore  # noqa: F401
