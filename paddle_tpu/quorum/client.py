"""Majority-lease client: campaign / renew / resign against an arbiter
group.

The safety argument, end to end:

- **one grant per epoch per node** (node-side, persisted): two
  concurrent campaigns can never both collect a strict majority at one
  epoch, because each node's vote for that epoch is spent exactly once
  and the two vote sets would have to overlap;
- **strictly increasing epochs**: a campaign first polls the reachable
  nodes' persisted maxima and bids max+1, and a node rejects any bid at
  or below its own maximum — so every successful election's epoch
  exceeds every epoch any earlier majority granted (the two majorities
  intersect in at least one node, and that node's persisted maximum
  fences the stale bid);
- **renew fails closed**: `renew()` returns True only with a strict
  majority of acks. A holder that cannot renew must treat its lease as
  dying and stop accepting writes no later than `lease.expires` — the
  arbiters will let a rival campaign through after that instant, never
  before (they refuse campaigns while a live rival record exists).

A failed campaign best-effort resigns the minority of grants it did
collect, so a lost race does not force the real winner to wait out a
stray lease. All calls fan out concurrently with short per-node
deadlines: one blackholed arbiter must not stall a renewal past the
lease (`ark.chaos.NetPartition` drills exactly this).
"""

from __future__ import annotations

import logging
import socket as _socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from .. import flags as _flags
from ..ark import chaos as _chaos
from ..observe import flight as _flight
from ..observe import metrics as _metrics

logger = logging.getLogger(__name__)

GRANTS_METRIC = "quorum_grants_total"
EPOCH_METRIC = "quorum_lease_epoch"
UNREACHABLE_METRIC = "quorum_arbiter_unreachable_total"
LEASE_OK_METRIC = "quorum_lease_ok"
MAJORITY_METRIC = "quorum_majority_acks"


class QuorumUnavailable(RuntimeError):
    """No strict majority of arbiter nodes answered."""


class QuorumLease:
    """A held lease: the resource, this holder's id (by convention the
    server's own endpoint, so `holder` doubles as a routable address for
    `PSClient` re-resolution), the fencing epoch, and the local expiry
    estimate (`granted_at + lease_s` on OUR monotonic clock — the
    conservative side of every arbiter's own expiry, which started
    later)."""

    __slots__ = ("resource", "holder", "epoch", "lease_s", "expires")

    def __init__(self, resource: str, holder: str, epoch: int,
                 lease_s: float, granted_at: float):
        self.resource = resource
        self.holder = holder
        self.epoch = int(epoch)
        self.lease_s = float(lease_s)
        self.expires = granted_at + float(lease_s)

    @property
    def live(self) -> bool:
        return time.monotonic() < self.expires

    def __repr__(self):
        return (f"QuorumLease({self.resource!r} -> {self.holder!r} "
                f"@e{self.epoch}, {'live' if self.live else 'EXPIRED'})")


class QuorumClient:
    """Thin fan-out client over an arbiter group. One socket per node,
    re-connected on failure; every logical operation talks to ALL nodes
    concurrently and counts acks against `majority` (strict: n//2+1)."""

    def __init__(self, endpoints: Sequence[str], deadline_s: float = 1.0,
                 connect_timeout_s: float = 0.5,
                 actor: Optional[str] = None):
        from ..pserver import rpc
        self._rpc = rpc
        # chaos attribution: which logical process OWNS this client.
        # Fan-out worker threads are shared, so without an explicit
        # actor a NetPartition rule against the owner's endpoint could
        # not see its quorum traffic (see ark/chaos.py actor identity).
        self.actor = actor
        self.endpoints = list(endpoints)
        if not self.endpoints:
            raise ValueError("QuorumClient needs at least one arbiter "
                             "endpoint")
        self.majority = len(self.endpoints) // 2 + 1
        self.deadline_s = float(deadline_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._socks: Dict[str, _socket.socket] = {}      # guarded_by: self._lock
        self._ep_locks: Dict[str, threading.Lock] = {}   # guarded_by: self._lock
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.endpoints)),
            thread_name_prefix="quorum-client")

    # -- transport --------------------------------------------------------
    def _sock(self, ep):
        with self._lock:
            s = self._socks.get(ep)
        if s is None:
            s = self._rpc.connect(ep, timeout=self.connect_timeout_s)
            with self._lock:
                self._socks[ep] = s
        return s

    def _drop(self, ep):
        with self._lock:
            s = self._socks.pop(ep, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _call_node(self, ep: str, cmd: str, payload: dict):
        """One request/reply against one node, bounded by `deadline_s`.
        Every quorum command is idempotent (grants re-ack, renews
        refresh, resigns no-op), so one blind retry on a stale cached
        socket is safe."""
        with _chaos.acting_as(self.actor or _chaos.current_actor()):
            return self._call_node_impl(ep, cmd, payload)

    def _call_node_impl(self, ep: str, cmd: str, payload: dict):
        # ONE in-flight request per node connection: the renewer
        # thread, a concurrent handover resign, and PSClient failover
        # holder() lookups may share this client — without the lock
        # their frames would interleave on the cached socket and each
        # would read the other's reply as its own verdict
        with self._lock:
            ep_lock = self._ep_locks.setdefault(ep, threading.Lock())
        last = None
        with ep_lock:
            for attempt in range(2):
                try:
                    s = self._sock(ep)
                    s.settimeout(self.deadline_s)
                    self._rpc.send_msg(s, (cmd, payload))
                    status, value = self._rpc.recv_msg(s)
                    s.settimeout(None)
                    if status != "ok":
                        raise RuntimeError(f"quorum {ep} {cmd}: {value}")
                    return value
                except (ConnectionError, EOFError, OSError,
                        _socket.timeout) as e:
                    self._drop(ep)
                    last = e
                    if isinstance(e, TimeoutError):
                        # a full deadline elapsed: the node is slow or
                        # blackholed, not a stale socket — a blind
                        # second deadline would stall the whole round
                        break
        if _flags.get_flag("observe"):
            _metrics.counter(
                UNREACHABLE_METRIC,
                "arbiter nodes unreachable per quorum operation").inc(
                    endpoint=ep, cmd=cmd)
        raise QuorumUnavailable(f"arbiter {ep} unreachable for {cmd}: "
                                f"{type(last).__name__}: {last}")

    def _fanout(self, cmd: str, payload: dict) -> Dict[str, object]:
        """cmd against every node concurrently; returns ep -> reply for
        the nodes that answered (unreachable nodes are simply absent)."""
        futs = {ep: self._pool.submit(self._call_node, ep, cmd,
                                      dict(payload))
                for ep in self.endpoints}
        out = {}
        for ep, f in futs.items():
            try:
                out[ep] = f.result()
            except (QuorumUnavailable, RuntimeError) as e:
                logger.debug("quorum node %s: %s", ep, e)
        return out

    # -- operations -------------------------------------------------------
    def campaign(self, resource: str, candidate: str, lease_s: float,
                 max_rounds: int = 3) -> Optional[QuorumLease]:
        """Try to win the lease on `resource`. Returns the lease on a
        strict-majority grant, or None when the election is lost (a
        rival holds it, this side is in a minority partition, or every
        round's epoch bid was stale). Raises QuorumUnavailable only when
        NO node answered at all."""
        epoch_bid = 0
        for _round in range(max_rounds):
            t0 = time.monotonic()
            views = self._fanout("q_epoch", {"resource": resource})
            if not views:
                self._meter_grant("unreachable")
                raise QuorumUnavailable(
                    f"campaign({resource!r}): no arbiter reachable")
            epoch_bid = max(epoch_bid,
                            max(int(v["epoch"]) for v in views.values())
                            ) + 1
            replies = self._fanout(
                "q_campaign", {"resource": resource, "candidate": candidate,
                               "epoch": epoch_bid, "lease_s": lease_s})
            grants = [ep for ep, v in replies.items() if v.get("granted")]
            if len(grants) >= self.majority:
                lease = QuorumLease(resource, candidate, epoch_bid,
                                    lease_s, granted_at=t0)
                self._meter_grant("granted", resource=resource,
                                  epoch=epoch_bid)
                _flight.note("quorum_grant", resource=resource,
                             holder=candidate, epoch=epoch_bid,
                             acks=len(grants))
                return lease
            # lost: release the minority grants so the real winner is
            # not blocked on our stray records, then decide whether a
            # higher bid could still win
            for ep in grants:
                try:
                    self._call_node(ep, "q_resign",
                                    {"resource": resource,
                                     "holder": candidate,
                                     "epoch": epoch_bid})
                except (QuorumUnavailable, RuntimeError):
                    pass
            reasons = {str(v.get("reason")) for v in replies.values()
                       if not v.get("granted")}
            if "held" in reasons or "boot_blackout" in reasons \
                    or not replies:
                # a live rival (or a blacked-out node) — retrying at a
                # higher epoch cannot help until their lease expires
                self._meter_grant(
                    "rejected" if "held" in reasons else "no_majority",
                    resource=resource)
                return None
            # stale_epoch everywhere reachable: re-poll and re-bid
            epoch_bid = max(
                [epoch_bid] + [int(v.get("epoch", 0))
                               for v in replies.values()])
        self._meter_grant("no_majority", resource=resource)
        return None

    def renew(self, lease: QuorumLease) -> bool:
        """Refresh `lease` on a strict majority. True extends
        `lease.expires` from the renewal's START instant (conservative);
        False means FAIL CLOSED — the holder must stop accepting writes
        by `lease.expires` at the latest."""
        t0 = time.monotonic()
        replies = self._fanout(
            "q_renew", {"resource": lease.resource, "holder": lease.holder,
                        "epoch": lease.epoch, "lease_s": lease.lease_s})
        acks = sum(1 for v in replies.values() if v.get("renewed"))
        fenced = any(str(v.get("reason")) == "fenced"
                     for v in replies.values() if not v.get("renewed"))
        if _flags.get_flag("observe"):
            _metrics.gauge(
                MAJORITY_METRIC,
                "arbiter acks on the most recent renew, per resource"
            ).set(float(acks), resource=lease.resource)
        if acks >= self.majority:
            lease.expires = t0 + lease.lease_s
            self._set_lease_ok(lease.resource, True, lease.epoch)
            return True
        self._set_lease_ok(lease.resource, False, lease.epoch)
        _flight.note("quorum_renew_failed", resource=lease.resource,
                     holder=lease.holder, epoch=lease.epoch, acks=acks,
                     fenced=fenced)
        return False

    def resign(self, lease: QuorumLease) -> None:
        self._fanout("q_resign", {"resource": lease.resource,
                                  "holder": lease.holder,
                                  "epoch": lease.epoch})
        self._set_lease_ok(lease.resource, None, lease.epoch)

    def holder(self, resource: str) -> Optional[dict]:
        """Best-effort view of who holds `resource`: the live record at
        the highest lease epoch among the reachable nodes, provided at
        least a majority of nodes answered (a minority view may be
        arbitrarily stale). Used by `PSClient` to find a shard's primary
        without guessing candidate endpoints."""
        replies = self._fanout("q_status", {"resource": resource})
        if len(replies) < self.majority:
            return None
        best = None
        for v in replies.values():
            if v.get("live") and v.get("holder"):
                if best is None or int(v["lease_epoch"]) > best["epoch"]:
                    best = {"holder": v["holder"],
                            "epoch": int(v["lease_epoch"])}
        return best

    def status(self, resource: str) -> List[dict]:
        """Raw per-node status rows (operator/debugging surface)."""
        return [dict(v, endpoint=ep)
                for ep, v in self._fanout("q_status",
                                          {"resource": resource}).items()]

    # -- metrics ----------------------------------------------------------
    def _meter_grant(self, outcome: str, resource: str = "",
                     epoch: int = 0):
        if not _flags.get_flag("observe"):
            return
        _metrics.counter(
            GRANTS_METRIC,
            "quorum campaign outcomes (granted / rejected / no_majority "
            "/ unreachable)").inc(outcome=outcome)
        if outcome == "granted" and resource:
            _metrics.gauge(
                EPOCH_METRIC,
                "fencing epoch of the most recent quorum grant, per "
                "resource").set(float(epoch), resource=resource)

    def _set_lease_ok(self, resource: str, ok=None, epoch: int = 0):
        if not _flags.get_flag("observe"):
            return
        g = _metrics.gauge(
            LEASE_OK_METRIC,
            "1 while a held quorum lease renews against a majority, 0 "
            "while renewal is failing (the quorum_loss detector's "
            "series)")
        if ok is None:
            g.set(1.0, resource=resource)   # resigned: not a loss
        else:
            g.set(1.0 if ok else 0.0, resource=resource)

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            socks = list(self._socks.values())
            self._socks.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
