/* C inference ABI for paddle_tpu.
 *
 * Capability parity with the reference's C inference surfaces:
 *   - paddle/legacy/capi (gradient_machine C API for embedding inference)
 *   - paddle/fluid/inference/api/paddle_inference_api.h:66-150
 *     (PaddleTensor / PaddlePredictor / CreatePaddlePredictor)
 *
 * TPU-native redesign: instead of re-implementing an interpreter in C++,
 * the shim embeds CPython and drives the SAME jit-compiled predictor the
 * Python Inferencer uses — one compiled XLA program per input shape, no
 * per-op dispatch. The ABI is pure C so any language with an FFI can load
 * libpaddle_tpu_capi.so against a model directory written by
 * fluid.io.save_inference_model.
 *
 * Thread-model: calls are serialized on the embedded interpreter's GIL.
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PD_FLOAT32 = 0,
  PD_INT32 = 1,
  PD_INT64 = 2,
} PD_DType;

typedef struct {
  const char* name;      /* feed target name (NULL = positional) */
  PD_DType dtype;
  const int64_t* shape;  /* dims, length `rank` */
  int rank;
  const void* data;      /* caller-owned contiguous buffer */
} PD_Tensor;

typedef void* PD_Predictor;
typedef void* PD_Results;

/* Load a model saved by fluid.io.save_inference_model. Returns NULL on
 * failure; PD_LastError() describes why. */
PD_Predictor PD_CreatePredictor(const char* model_dir);

/* Run inference. Returns a results handle (NULL on failure). */
PD_Results PD_PredictorRun(PD_Predictor pred, const PD_Tensor* inputs,
                           int num_inputs);

int PD_ResultsNum(PD_Results res);
const char* PD_ResultsName(PD_Results res, int i);
PD_DType PD_ResultsDType(PD_Results res, int i);
int PD_ResultsRank(PD_Results res, int i);
const int64_t* PD_ResultsShape(PD_Results res, int i);
const void* PD_ResultsData(PD_Results res, int i);   /* valid until destroy */
size_t PD_ResultsByteSize(PD_Results res, int i);

void PD_DestroyResults(PD_Results res);
void PD_DestroyPredictor(PD_Predictor pred);

/* Last error message for the calling thread ("" when none). */
const char* PD_LastError(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H_ */
