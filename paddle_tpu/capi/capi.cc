// C inference ABI implementation: embeds CPython and drives
// paddle_tpu.capi_runtime (see paddle_tpu_capi.h for the design note;
// reference analogs: legacy/capi/gradient_machine.cpp,
// inference/api/api_impl.cc NativePaddlePredictor).
//
// Build: python paddle_tpu/capi/build.py  ->  libpaddle_tpu_capi.so

#include "paddle_tpu_capi.h"

#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Owns the interpreter bootstrap. If the host process already runs Python
// (e.g. the ABI is exercised from ctypes in tests), we only take the GIL.
// When WE initialize the interpreter, the GIL is immediately released via
// PyEval_SaveThread so later calls — from ANY thread — can take it with
// PyGILState_Ensure; holding it across the return would deadlock every
// other thread of a multithreaded embedder.
void ensure_interpreter() {
  static bool bootstrapped = [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();  // release the GIL the init left us holding
    }
    return true;
  }();
  (void)bootstrapped;
}

class GILHolder {
 public:
  GILHolder() {
    ensure_interpreter();
    state_ = PyGILState_Ensure();
  }
  ~GILHolder() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_{};
};

struct Predictor {
  PyObject* handle;  // capi_runtime.Predictor instance
};

struct Results {
  PyObject* arrays;                       // list of (name, np.ndarray)
  std::vector<std::string> names;
  std::vector<std::vector<int64_t>> shapes;
  std::vector<PD_DType> dtypes;
  std::vector<Py_buffer> buffers;         // held until destroy
};

const char* dtype_str(PD_DType d) {
  switch (d) {
    case PD_FLOAT32: return "float32";
    case PD_INT32: return "int32";
    case PD_INT64: return "int64";
  }
  return "float32";
}

bool dtype_from_str(const char* s, PD_DType* out) {
  if (!strcmp(s, "float32")) { *out = PD_FLOAT32; return true; }
  if (!strcmp(s, "int32")) { *out = PD_INT32; return true; }
  if (!strcmp(s, "int64")) { *out = PD_INT64; return true; }
  return false;
}

size_t dtype_size(PD_DType d) { return d == PD_FLOAT32 || d == PD_INT32 ? 4 : 8; }

}  // namespace

extern "C" {

PD_Predictor PD_CreatePredictor(const char* model_dir) {
  GILHolder gil;
  g_last_error.clear();
  PyObject* mod = PyImport_ImportModule("paddle_tpu.capi_runtime");
  if (!mod) { set_error_from_python(); return nullptr; }
  PyObject* h = PyObject_CallMethod(mod, "create", "s", model_dir);
  Py_DECREF(mod);
  if (!h) { set_error_from_python(); return nullptr; }
  auto* p = new Predictor{h};
  return p;
}

PD_Results PD_PredictorRun(PD_Predictor pred, const PD_Tensor* inputs,
                           int num_inputs) {
  GILHolder gil;
  g_last_error.clear();
  auto* p = static_cast<Predictor*>(pred);
  if (!p) { g_last_error = "null predictor"; return nullptr; }

  PyObject* feed = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    const PD_Tensor& t = inputs[i];
    size_t n = dtype_size(t.dtype);
    for (int d = 0; d < t.rank; ++d) n *= static_cast<size_t>(t.shape[d]);
    PyObject* shape = PyTuple_New(t.rank);
    for (int d = 0; d < t.rank; ++d)
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
    // copy the caller's buffer into bytes: the runtime keeps arrays alive
    // past this call (jit donation), so no aliasing of caller memory
    PyObject* data = PyBytes_FromStringAndSize(
        static_cast<const char*>(t.data), static_cast<Py_ssize_t>(n));
    PyObject* entry = Py_BuildValue(
        "(sNsN)", t.name ? t.name : "", shape, dtype_str(t.dtype), data);
    PyList_SET_ITEM(feed, i, entry);
  }

  PyObject* out = PyObject_CallMethod(p->handle, "run", "(N)", feed);
  if (!out) { set_error_from_python(); return nullptr; }

  auto* res = new Results{};
  res->arrays = out;  // list of (name, dtype_str, ndarray)
  Py_ssize_t n = PyList_Size(out);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyList_GetItem(out, i);
    const char* name = PyUnicode_AsUTF8(PyTuple_GetItem(item, 0));
    const char* dts = PyUnicode_AsUTF8(PyTuple_GetItem(item, 1));
    PyObject* arr = PyTuple_GetItem(item, 2);
    PD_DType dt = PD_FLOAT32;
    if (!dtype_from_str(dts, &dt)) {
      g_last_error = std::string("unsupported output dtype ") + dts;
      PD_DestroyResults(res);
      return nullptr;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(arr, &view, PyBUF_C_CONTIGUOUS) != 0) {
      set_error_from_python();
      PD_DestroyResults(res);
      return nullptr;
    }
    res->names.emplace_back(name);
    res->dtypes.push_back(dt);
    std::vector<int64_t> shp(view.ndim);
    for (int d = 0; d < view.ndim; ++d) shp[d] = view.shape[d];
    res->shapes.push_back(std::move(shp));
    res->buffers.push_back(view);
  }
  return res;
}

int PD_ResultsNum(PD_Results r) {
  auto* res = static_cast<Results*>(r);
  return res ? static_cast<int>(res->names.size()) : 0;
}

const char* PD_ResultsName(PD_Results r, int i) {
  return static_cast<Results*>(r)->names[i].c_str();
}

PD_DType PD_ResultsDType(PD_Results r, int i) {
  return static_cast<Results*>(r)->dtypes[i];
}

int PD_ResultsRank(PD_Results r, int i) {
  return static_cast<int>(static_cast<Results*>(r)->shapes[i].size());
}

const int64_t* PD_ResultsShape(PD_Results r, int i) {
  return static_cast<Results*>(r)->shapes[i].data();
}

const void* PD_ResultsData(PD_Results r, int i) {
  return static_cast<Results*>(r)->buffers[i].buf;
}

size_t PD_ResultsByteSize(PD_Results r, int i) {
  return static_cast<size_t>(static_cast<Results*>(r)->buffers[i].len);
}

void PD_DestroyResults(PD_Results r) {
  auto* res = static_cast<Results*>(r);
  if (!res) return;
  GILHolder gil;
  for (auto& b : res->buffers) PyBuffer_Release(&b);
  Py_XDECREF(res->arrays);
  delete res;
}

void PD_DestroyPredictor(PD_Predictor pred) {
  auto* p = static_cast<Predictor*>(pred);
  if (!p) return;
  GILHolder gil;
  Py_XDECREF(p->handle);
  delete p;
}

const char* PD_LastError(void) { return g_last_error.c_str(); }

}  // extern "C"
