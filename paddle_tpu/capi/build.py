"""Build libpaddle_tpu_capi.so (reference analog: the capi cmake target,
legacy/capi/CMakeLists.txt). Uses python3-config for the embed flags;
pybind11 is deliberately not required — the shim is plain CPython C API.

Usage: python paddle_tpu/capi/build.py [outdir]
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig


def build(outdir: str | None = None) -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    outdir = outdir or here
    os.makedirs(outdir, exist_ok=True)
    out = os.path.join(outdir, "libpaddle_tpu_capi.so")
    include = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR")
    ldlib = sysconfig.get_config_var("LDLIBRARY") or ""
    # embed link flags: prefer python3-config --embed when available
    ldflags = [f"-L{libdir}"] if libdir else []
    ver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    ldflags.append(f"-l{ver}")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
           os.path.join(here, "capi.cc"), f"-I{include}", f"-I{here}",
           "-o", out] + ldflags
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    print(build(sys.argv[1] if len(sys.argv) > 1 else None))
