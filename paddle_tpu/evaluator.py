"""Graph-building Evaluator API (reference:
python/paddle/fluid/evaluator.py — deprecated there in favor of
fluid.metrics, kept for source compatibility).

Each evaluator appends its per-batch metric ops to the current program at
construction time and accumulates host-side across `eval()` epochs via the
matching fluid.metrics class — the TPU-era replacement for the reference's
in-graph accumulator variables (reset meant running zero-fill ops; here
reset is a host-side counter clear)."""

from __future__ import annotations

import numpy as np

from . import metrics as _metrics
from .annotations import deprecated
from . import layers

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP", "Accuracy"]


class Evaluator:
    """Base: `metrics` holds the per-batch fetch variables; feed their
    fetched values to `update`; `eval()` returns the accumulated result."""

    def __init__(self, name=None):
        self._acc = None
        self.metrics = []

    def reset(self, executor=None, reset_program=None):
        self._acc.reset()

    def update(self, *batch_values):
        self._acc.update(*[np.asarray(v) for v in batch_values])

    def eval(self, executor=None, eval_program=None):
        return self._acc.eval()


class Accuracy(Evaluator):
    @deprecated("2018", "fluid.metrics.Accuracy")
    def __init__(self, input, label, k=1, **kwargs):
        super().__init__(**kwargs)
        self._acc = _metrics.Accuracy()
        acc = layers.accuracy(input=input, label=label, k=k)
        self.metrics.append(acc)

    def update(self, acc_value, weight):
        self._acc.update(float(np.asarray(acc_value).reshape(-1)[0]),
                         int(weight))


class ChunkEvaluator(Evaluator):
    @deprecated("2018", "fluid.metrics.ChunkEvaluator")
    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, **kwargs):
        super().__init__(**kwargs)
        self._acc = _metrics.ChunkEvaluator()
        precision, recall, f1, ninfer, nlabel, ncorrect = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.metrics.extend([ninfer, nlabel, ncorrect])

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self._acc.update(num_infer_chunks, num_label_chunks,
                         num_correct_chunks)


class EditDistance(Evaluator):
    @deprecated("2018", "fluid.metrics.EditDistance")
    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super().__init__(**kwargs)
        self._acc = _metrics.EditDistance()
        dist, seq_num = layers.edit_distance(input=input, label=label,
                                             ignored_tokens=ignored_tokens)
        self.metrics.extend([dist, seq_num])

    def update(self, distances, seq_num):
        self._acc.update(distances, seq_num)


class DetectionMAP(Evaluator):
    @deprecated("2018", "fluid.metrics.DetectionMAP")
    def __init__(self, input, gt_label, gt_box=None, gt_difficult=None,
                 class_num=None, background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral", **kwargs):
        super().__init__(**kwargs)
        self._acc = _metrics.DetectionMAP()
        # padded static-shape contract (ops/detection.py _detection_map):
        # input [B,D,6] detections, gt_label [B,G,6] padded ground truth
        m = layers.detection_map(input, gt_label, class_num=class_num,
                                 background_label=background_label,
                                 overlap_threshold=overlap_threshold,
                                 evaluate_difficult=evaluate_difficult,
                                 ap_version=ap_version)
        self.metrics.append(m)

    def update(self, value, weight):
        self._acc.update(value, weight)
