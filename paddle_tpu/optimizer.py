"""Optimizers: backward + per-parameter update ops appended to the program.

Capability parity with reference python/paddle/fluid/optimizer.py (Optimizer
base :36, accumulators, `_create_optimization_pass` :188, `minimize` :245 =
append_backward + regularization + clip + apply_gradients; SGD :271,
Momentum :312, Adagrad :386, Adam :452, Adamax :593, DecayedAdagrad :714,
Adadelta :785, RMSProp, Ftrl, ModelAverage).

TPU-native: update ops lower into the same XLA step as fwd/bwd, buffers are
donated, so the whole training iteration is one fused device program.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional

from .core import ir
from .core.backward import append_backward
from .layer_helper import LayerHelper
from . import initializer as init
from . import unique_name
from .regularizer import append_regularization_ops
from .clip import append_gradient_clip_ops, error_clip_callback


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._accumulators: Dict[str, Dict[str, ir.Variable]] = {}
        self._lr_var: Optional[ir.Variable] = None
        self.helper = None

    # -- learning rate ----------------------------------------------------
    def _create_lr_var(self, program) -> ir.Variable:
        if isinstance(self._learning_rate, ir.Variable):
            return self._learning_rate
        helper = LayerHelper("learning_rate")
        name = unique_name.generate("learning_rate")
        gb = program.global_block()
        var = gb.create_var(name=name, shape=(1,), dtype="float32",
                            persistable=True, stop_gradient=True)
        helper.set_variable_initializer(
            var, init.ConstantInitializer(float(self._learning_rate)))
        return var

    def _global_learning_rate(self):
        return self._lr_var

    # -- accumulators (reference optimizer.py:103-166) --------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        var_name = unique_name.generate(f"{param.name}_{name}")
        gb = param.block.program.global_block()
        var = gb.create_var(name=var_name, shape=shape or param.shape,
                            dtype=dtype or param.dtype, persistable=True,
                            stop_gradient=True)
        helper.set_variable_initializer(var, init.ConstantInitializer(fill_value))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks per optimizer ----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- the pass ----------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        self._lr_var = self._create_lr_var(program)
        block = program.global_block()
        self._create_accumulators(block,
                                  [p for p, g in parameters_and_grads if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, param_and_grad))
        self._finish_update(block, parameters_and_grads)
        # bump the LR-decay global step if a schedule created one
        if "@LR_DECAY_COUNTER@" in block.vars:
            ctr = block.vars["@LR_DECAY_COUNTER@"]
            block.append_op("increment", inputs={"X": [ctr.name]},
                            outputs={"Out": [ctr.name]}, attrs={"step": 1.0})
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """append_backward + regularization + clip + update ops
        (reference optimizer.py:245)."""
        block = loss.block.program.global_block()
        n0 = len(block.ops)
        params_grads = append_backward(loss, parameter_list=parameter_list,
                                       no_grad_set=no_grad_set)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        # role-tag everything minimize appended (clip/reg/lr/update ops);
        # grad ops were already tagged "backward" by append_backward. Eval
        # clones strip by role (ir._set_inference_mode).
        for op in block.ops[n0:]:
            op.attrs.setdefault("__role__", "optimize")
        return optimize_ops, params_grads

    def _lr_for_param(self, param):
        """Per-parameter lr multiplier (ParamAttr.learning_rate). A
        Variable is used directly — append_LARS stores the per-layer
        decayed lr here (reference optimizer.py _create_param_lr
        special-cases Variable the same way)."""
        from .core import ir
        mult = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if isinstance(mult, ir.Variable):
            return mult
        if mult == 1.0:
            return self._lr_var
        return self._lr_var * float(mult)


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name]})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": [p.name], "Grad": [g.name], "Velocity": [v.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "VelocityOut": [v.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "adam"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1 = self._get_accumulator("beta1_pow_acc", p)
        b2 = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adam",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
                    "Moment2": [m2.name], "Beta1Pow": [b1.name],
                    "Beta2Pow": [b2.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "Moment1Out": [m1.name],
                     "Moment2Out": [m2.name], "Beta1PowOut": [b1.name],
                     "Beta2PowOut": [b2.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        u = self._get_accumulator("inf_norm", p)
        b1 = self._get_accumulator("beta1_pow_acc", p)
        return block.append_op(
            "adamax",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "InfNorm": [u.name], "Beta1Pow": [b1.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name],
                     "InfNormOut": [u.name], "Beta1PowOut": [b1.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "MomentOut": [m.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        g2 = self._get_accumulator("__avg_squared_grad", p)
        u2 = self._get_accumulator("__avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "AvgSquaredGrad": [g2.name], "AvgSquaredUpdate": [u2.name]},
            outputs={"ParamOut": [p.name], "AvgSquaredGradOut": [g2.name],
                     "AvgSquaredUpdateOut": [u2.name]},
            attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        inputs = {"Param": [p.name], "Grad": [g.name],
                  "MeanSquare": [ms.name], "Moment": [mom.name],
                  "LearningRate": [self._lr_for_param(p).name]}
        outputs = {"ParamOut": [p.name], "MeanSquareOut": [ms.name],
                   "MomentOut": [mom.name]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            inputs["MeanGrad"] = [mg.name]
            outputs["MeanGradOut"] = [mg.name]
        return block.append_op(
            "rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={"Param": [p.name], "Grad": [g.name],
                    "SquaredAccumulator": [sq.name],
                    "LinearAccumulator": [lin.name],
                    "LearningRate": [self._lr_for_param(p).name]},
            outputs={"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging for eval (reference
    optimizer.py:1111 + average_accumulates_op.h).

    Construct AFTER ``optimizer.minimize(loss)`` on the training program:
    it appends one ``average_accumulates`` op per parameter to the main
    program (the sums update in the same fused XLA step as the training
    update), and builds standalone apply/restore programs that swap the
    averaged values into the parameters around an eval pass::

        with model_average.apply(exe, scope=scope):
            ... run eval programs: params hold the window average ...
        # params restored afterwards
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, main_program=None, **kw):
        super().__init__(0.0, **kw)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        program = main_program or ir.default_main_program()
        self._backups: Dict[str, str] = {}

        params = [p for p in program.global_block().all_parameters()
                  if getattr(p, "do_model_average", None) is not False]
        block = program.global_block()
        self._create_accumulators(block, params)
        for p in params:
            self._append_accumulate_op(block, p)

        self.apply_program = self._build_apply_program(params)
        self.restore_program = self._build_restore_program(params)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("sum_1", p)
            self._add_accumulator("sum_2", p)
            self._add_accumulator("sum_3", p)
            for ctr in ("num_accumulates", "old_num_accumulates",
                        "num_updates"):
                self._add_accumulator(ctr, p, dtype="int32", shape=(1,))

    def _append_accumulate_op(self, block, p):
        accs = {n: self._get_accumulator(n, p)
                for n in ("sum_1", "sum_2", "sum_3", "num_accumulates",
                          "old_num_accumulates", "num_updates")}
        block.append_op(
            "average_accumulates",
            inputs={"param": [p.name],
                    **{f"in_{n}": [v.name] for n, v in accs.items()}},
            outputs={f"out_{n}": [v.name] for n, v in accs.items()},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window,
                   "__role__": "optimize"})

    def _clone_into(self, block, var):
        return block.create_var(name=var.name, shape=var.shape,
                                dtype=var.dtype, persistable=True,
                                stop_gradient=True)

    def _build_apply_program(self, params):
        from . import layers
        prog = ir.Program()
        with ir.program_guard(prog), unique_name.guard():
            block = prog.global_block()
            for p in params:
                param = self._clone_into(block, p)
                accs = [self._clone_into(block, self._get_accumulator(n, p))
                        for n in ("sum_1", "sum_2", "sum_3")]
                ctrs = [self._clone_into(block, self._get_accumulator(n, p))
                        for n in ("num_accumulates", "old_num_accumulates")]
                backup = block.create_var(
                    name=unique_name.generate(p.name + ".model_average_bak"),
                    shape=p.shape, dtype=p.dtype, persistable=True,
                    stop_gradient=True)
                self._backups[p.name] = backup.name
                layers.assign(input=param, output=backup)
                total = layers.cast(layers.sums(ctrs), dtype=param.dtype)
                avg = layers.elementwise_div(x=layers.sums(accs), y=total)
                layers.assign(input=avg, output=param)
        return prog

    def _build_restore_program(self, params):
        from . import layers
        prog = ir.Program()
        with ir.program_guard(prog), unique_name.guard():
            block = prog.global_block()
            for p in params:
                param = self._clone_into(block, p)
                backup = block.create_var(name=self._backups[p.name],
                                          shape=p.shape, dtype=p.dtype,
                                          persistable=True,
                                          stop_gradient=True)
                layers.assign(input=backup, output=param)
        return prog

    @contextmanager
    def apply(self, executor, need_restore=True, scope=None):
        """Swap window-averaged values into the parameters
        (reference optimizer.py:1247)."""
        kw = {"scope": scope} if scope is not None else {}
        executor.run(self.apply_program, **kw)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor, scope=scope)

    def restore(self, executor, scope=None):
        """Restore the pre-apply parameter values (reference
        optimizer.py:1268)."""
        kw = {"scope": scope} if scope is not None else {}
        executor.run(self.restore_program, **kw)


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adagrad = AdagradOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
