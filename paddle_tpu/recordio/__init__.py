"""RecordIO: chunked record file format for fast reader pipelines.

Capability parity with the reference's C++ recordio library (reference:
paddle/fluid/recordio/ — kMagicNumber header.h:23, Compressor enum
header.h:25, Chunk::Write chunk.h:36, Scanner, writer.cc; python writer
bound via pybind recordio.cc).

Layout per chunk (all u32 little-endian, matching the reference header
fields): MAGIC, num_records, checksum (crc32 of the payload), compressor,
payload_size, then the payload = concatenated [u32 length | bytes]
records. Compressor 0 = none, 1 = snappy (pure-python codec in
snappy_codec.py: real greedy-match encoder + framed-stream layer matching
the reference's snappystream format, header CRC over the compressed bytes
as chunk.cc places it), 2 = gzip (zlib).
The byte-level hot paths (checksums, record splitting, and the snappy
match/replay loops) run in a small C++ library (native.cc) compiled
lazily with g++; pure-python fallbacks keep the format usable without a
toolchain."""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import tempfile
import zlib
from typing import Iterator, List, Optional

logger = logging.getLogger(__name__)

MAGIC = 0x01020304
NO_COMPRESS = 0
SNAPPY = 1      # reference vendored C snappy; here snappy_codec.py
GZIP = 2

_HDR = struct.Struct("<IIIII")   # magic, num_records, checksum, comp, size


# -- native fast path -------------------------------------------------------

_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    here = os.path.dirname(os.path.abspath(__file__))
    cache = os.path.join(os.path.expanduser("~/.cache/paddle_tpu"),
                         "librecordio.so")
    src = os.path.join(here, "native.cc")
    try:
        if not os.path.exists(cache) or (os.path.getmtime(cache)
                                         < os.path.getmtime(src)):
            os.makedirs(os.path.dirname(cache), exist_ok=True)
            subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-o", cache,
                            src], check=True, capture_output=True)
        lib = ctypes.CDLL(cache)
        lib.rio_crc32.restype = ctypes.c_uint32
        lib.rio_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.rio_split_records.restype = ctypes.c_long
        lib.rio_split_records.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t]
        if hasattr(lib, "rio_snappy_compress"):  # round-5 additions
            lib.rio_crc32c.restype = ctypes.c_uint32
            lib.rio_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            for fn in (lib.rio_snappy_compress, lib.rio_snappy_decompress):
                fn.restype = ctypes.c_long
                fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                               ctypes.c_char_p, ctypes.c_size_t]
        _native = lib
    except Exception as e:  # no g++ / sandbox: python fallback
        logger.info("recordio: native library unavailable (%s); using "
                    "python fallback", e)
        _native = False
    return _native


def _crc32(data: bytes) -> int:
    lib = _load_native()
    if lib:
        return lib.rio_crc32(data, len(data))
    return zlib.crc32(data) & 0xFFFFFFFF


def _split_records(payload: bytes) -> List[bytes]:
    lib = _load_native()
    if lib:
        cap = max(16, len(payload) // 4)
        offs = (ctypes.c_uint32 * cap)()
        lens = (ctypes.c_uint32 * cap)()
        n = lib.rio_split_records(payload, len(payload), offs, lens, cap)
        if n == -1:
            raise IOError("recordio: malformed chunk payload")
        if n >= 0:
            return [payload[offs[i]:offs[i] + lens[i]] for i in range(n)]
        # n == -2: more records than cap (all empty records) — fall through
    out = []
    pos, n = 0, len(payload)
    while pos < n:
        if pos + 4 > n:
            raise IOError("recordio: malformed chunk payload")
        (ln,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        if pos + ln > n:
            raise IOError("recordio: malformed chunk payload")
        out.append(payload[pos:pos + ln])
        pos += ln
    return out


# -- chunk ------------------------------------------------------------------

def _write_chunk(fo, records: List[bytes], compressor: int):
    payload = b"".join(struct.pack("<I", len(r)) + r for r in records)
    if compressor == GZIP:
        checksum = _crc32(payload)
        payload = zlib.compress(payload)
    elif compressor == SNAPPY:
        # reference format: snappystream FRAMED payload, header CRC over
        # the COMPRESSED bytes (chunk.cc Crc32Stream after compression)
        from . import snappy_codec
        payload = snappy_codec.compress_framed(payload)
        checksum = _crc32(payload)
    elif compressor == NO_COMPRESS:
        checksum = _crc32(payload)
    else:
        raise ValueError(f"unsupported compressor {compressor}")
    fo.write(_HDR.pack(MAGIC, len(records), checksum, compressor,
                       len(payload)))
    fo.write(payload)


def _read_chunk(fi) -> Optional[List[bytes]]:
    hdr = fi.read(_HDR.size)
    if not hdr:
        return None
    if len(hdr) < _HDR.size:
        raise IOError("recordio: truncated chunk header")
    magic, num, checksum, comp, size = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise IOError(f"recordio: bad magic {magic:#x}")
    payload = fi.read(size)
    if len(payload) < size:
        raise IOError("recordio: truncated chunk payload")
    if comp == GZIP:
        payload = zlib.decompress(payload)
        if _crc32(payload) != checksum:
            raise IOError("recordio: checksum mismatch")
    elif comp == SNAPPY:
        from . import snappy_codec
        wire = payload
        payload = (snappy_codec.decompress_framed(wire)
                   if snappy_codec.is_framed(wire)
                   else snappy_codec.decompress(wire))
        # reference placement: CRC over the compressed stream; rounds 3-4
        # of this repo wrote raw-snappy payloads with CRC over the
        # DEcompressed bytes — accept either, exact match required
        if _crc32(wire) != checksum and _crc32(payload) != checksum:
            raise IOError("recordio: checksum mismatch")
    elif comp == NO_COMPRESS:
        if _crc32(payload) != checksum:
            raise IOError("recordio: checksum mismatch")
    else:
        raise IOError(f"recordio: unsupported compressor {comp}")
    records = _split_records(payload)
    if len(records) != num:
        raise IOError(f"recordio: header claims {num} records, "
                      f"found {len(records)}")
    return records


# -- public API (reference writer.h / scanner.h shapes) ---------------------

class Writer:
    """reference recordio::Writer: buffer records, flush a chunk every
    max_num_records (or max_chunk_size bytes)."""

    def __init__(self, path_or_file, max_num_records: int = 1000,
                 max_chunk_size: int = 8 << 20, compressor: int = NO_COMPRESS):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "wb") if self._own else path_or_file
        self.max_num_records = max_num_records
        self.max_chunk_size = max_chunk_size
        self.compressor = compressor
        self._records: List[bytes] = []
        self._nbytes = 0

    def write(self, record: bytes):
        if isinstance(record, str):
            record = record.encode()
        self._records.append(bytes(record))
        self._nbytes += len(record)
        if (len(self._records) >= self.max_num_records
                or self._nbytes >= self.max_chunk_size):
            self.flush()

    def flush(self):
        if self._records:
            _write_chunk(self._f, self._records, self.compressor)
            self._records, self._nbytes = [], 0

    def close(self):
        self.flush()
        if self._own:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Scanner:
    """reference recordio::Scanner: iterate records across chunks."""

    def __init__(self, path_or_file):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "rb") if self._own else path_or_file

    def __iter__(self) -> Iterator[bytes]:
        while True:
            records = _read_chunk(self._f)
            if records is None:
                return
            yield from records

    def close(self):
        if self._own:
            self._f.close()


def write_file(path, record_iter, **kw):
    """Convenience: dump an iterable of byte records to `path`."""
    with Writer(path, **kw) as w:
        n = 0
        for r in record_iter:
            w.write(r)
            n += 1
    return n


def reader(path):
    """Reader-creator over a RecordIO file (fits paddle_tpu.reader
    decorators)."""
    def _r():
        s = Scanner(path)
        try:
            yield from iter(s)
        finally:
            s.close()
    return _r
