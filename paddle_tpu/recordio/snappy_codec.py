"""Pure-Python snappy codec for RecordIO chunk payloads.

The reference writes RecordIO snappy chunks through the snappystream
library — the snappy FRAMED stream format ('sNaPpY' stream identifier,
per-frame masked CRC32C) wrapping raw-snappy frame bodies — and its
chunk header CRC covers the COMPRESSED payload (reference:
paddle/fluid/recordio/chunk.cc Chunk::Write `snappy::oSnappyStream` +
`Crc32Stream(sout)` after compression; header.h:25 kSnappy). This build
has no snappy wheel and zero egress, so both layers are implemented
directly from the public format specs:

- ``decompress`` is a COMPLETE raw-snappy decoder (literals + all three
  copy-element forms, including overlapping copies).
- ``compress`` is a real encoder: greedy hash-table matching over a 64 KB
  window emitting copy elements, the same scheme as C snappy — not the
  round-4 literal-only stub.
- ``compress_framed`` / ``decompress_framed`` / ``is_framed`` implement
  the framing format the reference actually writes (stream identifier,
  compressed/uncompressed frames, masked CRC32C per frame), so
  reference-written chunk payloads round-trip into this reader and
  vice versa.
"""

from __future__ import annotations

import ctypes
import struct


class SnappyError(IOError):
    pass


def _native():
    """The librecordio.so hot path (native.cc), if buildable: the greedy
    matcher and copy-replay are per-byte loops that belong in C++ — the
    pure-python paths below stay as the no-toolchain fallback and as the
    executable spec the tests cross-check against."""
    from . import _load_native

    lib = _load_native()
    return lib if lib and hasattr(lib, "rio_snappy_compress") else None


def _read_varint32(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise SnappyError("snappy: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & 0xFFFFFFFF, pos
        shift += 7
        if shift > 32:
            raise SnappyError("snappy: varint too long")


def _write_varint32(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(buf: bytes) -> bytes:
    """Full snappy raw-format decoder (native hot path when available)."""
    lib = _native()
    if lib is not None:
        expected, _ = _read_varint32(buf, 0)
        out = ctypes.create_string_buffer(max(expected, 1))
        m = lib.rio_snappy_decompress(bytes(buf), len(buf), out, expected)
        if m >= 0:
            return out.raw[:m]
        raise SnappyError("snappy: malformed stream"
                          if m == -1 else "snappy: length mismatch")
    return _decompress_py(buf)


def _decompress_py(buf: bytes) -> bytes:
    """Pure-python decoder — the executable spec and no-g++ fallback."""
    expected, pos = _read_varint32(buf, 0)
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                       # literal
            ln = tag >> 2
            if ln >= 60:                    # 60..63: length in next 1..4 bytes
                nbytes = ln - 59
                if pos + nbytes > n:
                    raise SnappyError("snappy: truncated literal length")
                ln = int.from_bytes(buf[pos:pos + nbytes], "little")
                pos += nbytes
            ln += 1
            if pos + ln > n:
                raise SnappyError("snappy: truncated literal")
            out += buf[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:                       # copy, 1-byte offset
            ln = ((tag >> 2) & 0x07) + 4
            if pos >= n:
                raise SnappyError("snappy: truncated copy-1")
            off = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            ln = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("snappy: truncated copy-2")
            off = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            ln = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("snappy: truncated copy-4")
            off = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise SnappyError("snappy: invalid copy offset")
        # overlapping copies are byte-at-a-time by spec
        start = len(out) - off
        if off >= ln:
            out += out[start:start + ln]
        else:
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != expected:
        raise SnappyError(
            f"snappy: length mismatch (got {len(out)}, expected {expected})")
    return bytes(out)


def _emit_literal(out: bytearray, buf: bytes, start: int, end: int):
    while start < end:
        ln = min(1 << 16, end - start)
        if ln <= 60:
            out.append((ln - 1) << 2)
        elif ln <= 0x100:
            out.append(60 << 2)
            out += (ln - 1).to_bytes(1, "little")
        else:
            out.append(61 << 2)
            out += (ln - 1).to_bytes(2, "little")
        out += buf[start:start + ln]
        start += ln


def _emit_copy(out: bytearray, off: int, ln: int):
    # long matches split into <=64-byte copies (C snappy does the same)
    while ln >= 68:
        out.append((59 << 2) | 2)                      # copy-2, len 60
        out += off.to_bytes(2, "little")
        ln -= 60
    if ln > 64:
        out.append((59 << 2) | 2)
        out += off.to_bytes(2, "little")
        ln -= 60
    if 4 <= ln <= 11 and off < 2048:
        out.append(((ln - 4) << 2) | ((off >> 8) << 5) | 1)
        out.append(off & 0xFF)
    else:
        out.append(((ln - 1) << 2) | 2)
        out += off.to_bytes(2, "little")


_HASH_MUL = 0x1E35A7BD                                 # C snappy's multiplier


def compress(buf: bytes) -> bytes:
    """Raw-snappy encoder with greedy hash-table matching (native hot
    path when available): 4-byte prefixes hash into a table of recent
    positions; a >=4-byte match within the 64 KB offset window becomes a
    copy element, everything between matches a literal."""
    lib = _native()
    if lib is not None:
        n = len(buf)
        cap = 16 + n + 3 * (n // 65536 + 1)
        out = ctypes.create_string_buffer(cap)
        m = lib.rio_snappy_compress(bytes(buf), n, out, cap)
        if m > 0:
            return out.raw[:m]
    return _compress_py(buf)


def _compress_py(buf: bytes) -> bytes:
    """Pure-python encoder — the executable spec and no-g++ fallback."""
    n = len(buf)
    out = bytearray(_write_varint32(n))
    if n < 4:
        if n:
            _emit_literal(out, buf, 0, n)
        return bytes(out)
    shift = 32 - 14                                    # 16384-entry table
    table = {}
    pos, lit_start = 0, 0
    limit = n - 3                                      # last 4-byte prefix
    u32 = struct.Struct("<I").unpack_from
    while pos < limit:
        h = ((u32(buf, pos)[0] * _HASH_MUL) & 0xFFFFFFFF) >> shift
        cand = table.get(h)
        table[h] = pos
        if (cand is not None and pos - cand <= 0xFFFF
                and buf[cand:cand + 4] == buf[pos:pos + 4]):
            # extend the match (cand+m can run past pos: overlapping
            # copies are legal and the decoder replays them byte-wise)
            m = 4
            while pos + m < n and buf[cand + m] == buf[pos + m]:
                m += 1
            _emit_literal(out, buf, lit_start, pos)
            _emit_copy(out, pos - cand, m)
            pos += m
            lit_start = pos
        else:
            pos += 1
    _emit_literal(out, buf, lit_start, n)
    return bytes(out)


# -- framing format (what the reference's snappystream writes) --------------

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_MAX_FRAME = 65536                                     # uncompressed bytes


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the checksum the framing format mandates."""
    lib = _native()
    if lib is not None:
        return lib.rio_crc32c(bytes(data), len(data))
    return _crc32c_py(data)


def _crc32c_py(data: bytes) -> int:
    tab = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_CRC32C_TABLE = None


def _crc32c_table():
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        tab = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
            tab.append(crc)
        _CRC32C_TABLE = tab
    return _CRC32C_TABLE


def _mask_crc(crc: int) -> int:
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def is_framed(buf: bytes) -> bool:
    return buf[:len(_STREAM_ID)] == _STREAM_ID


def compress_framed(buf: bytes) -> bytes:
    """Snappy framing-format stream: identifier + per-frame masked CRC32C
    + raw-snappy frame bodies — byte-compatible with what the reference's
    snappystream emits/consumes."""
    out = bytearray(_STREAM_ID)
    for start in range(0, len(buf), _MAX_FRAME) or [0]:
        frame = buf[start:start + _MAX_FRAME]
        crc = _mask_crc(_crc32c(frame))
        body = compress(frame)
        if len(body) < len(frame):
            typ = 0x00                                 # compressed frame
        else:
            typ, body = 0x01, frame                    # incompressible
        out.append(typ)
        out += (len(body) + 4).to_bytes(3, "little")
        out += crc.to_bytes(4, "little")
        out += body
    return bytes(out)


def decompress_framed(buf: bytes) -> bytes:
    """Decode a framing-format stream, verifying each frame's CRC32C."""
    if not is_framed(buf):
        raise SnappyError("snappy: missing stream identifier")
    pos = len(_STREAM_ID)
    out = bytearray()
    n = len(buf)
    while pos < n:
        if pos + 4 > n:
            raise SnappyError("snappy: truncated frame header")
        typ = buf[pos]
        ln = int.from_bytes(buf[pos + 1:pos + 4], "little")
        pos += 4
        if pos + ln > n:
            raise SnappyError("snappy: truncated frame")
        body = buf[pos:pos + ln]
        pos += ln
        if typ in (0x00, 0x01):                        # (un)compressed data
            if ln < 4:
                raise SnappyError("snappy: frame too short for checksum")
            want = int.from_bytes(body[:4], "little")
            data = decompress(body[4:]) if typ == 0x00 else bytes(body[4:])
            if _mask_crc(_crc32c(data)) != want:
                raise SnappyError("snappy: frame CRC32C mismatch")
            out += data
        elif typ == 0xFF:                              # repeated stream id
            if body != _STREAM_ID[4:]:
                raise SnappyError("snappy: bad stream identifier frame")
        elif 0x80 <= typ <= 0xFD or typ == 0xFE:       # skippable / padding
            continue
        else:                                          # 0x02..0x7F reserved
            raise SnappyError(f"snappy: unknown frame type {typ:#x}")
    return bytes(out)
