"""Pure-Python snappy codec for RecordIO chunk payloads.

The reference vendors Google snappy for its RecordIO compressor code 1
(reference: paddle/fluid/recordio/header.h:25 kSnappy, chunk.cc). This
build has no snappy wheel and zero egress, so the format is implemented
directly from the public framing spec:

- ``decompress`` is a COMPLETE decoder (literals + all three copy-element
  forms, including overlapping copies), so chunk payloads written by the
  reference's real snappy round-trip into this reader.
- ``compress`` emits spec-compliant literal-only streams: valid snappy
  that any decoder (including the reference's) reads back; it trades the
  size win for zero vendored C code. Use GZIP when on-disk size matters.
"""

from __future__ import annotations


class SnappyError(IOError):
    pass


def _read_varint32(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise SnappyError("snappy: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & 0xFFFFFFFF, pos
        shift += 7
        if shift > 32:
            raise SnappyError("snappy: varint too long")


def _write_varint32(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(buf: bytes) -> bytes:
    """Full snappy raw-format decoder."""
    expected, pos = _read_varint32(buf, 0)
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                       # literal
            ln = tag >> 2
            if ln >= 60:                    # 60..63: length in next 1..4 bytes
                nbytes = ln - 59
                if pos + nbytes > n:
                    raise SnappyError("snappy: truncated literal length")
                ln = int.from_bytes(buf[pos:pos + nbytes], "little")
                pos += nbytes
            ln += 1
            if pos + ln > n:
                raise SnappyError("snappy: truncated literal")
            out += buf[pos:pos + ln]
            pos += ln
            continue
        if kind == 1:                       # copy, 1-byte offset
            ln = ((tag >> 2) & 0x07) + 4
            if pos >= n:
                raise SnappyError("snappy: truncated copy-1")
            off = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            ln = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("snappy: truncated copy-2")
            off = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            ln = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("snappy: truncated copy-4")
            off = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise SnappyError("snappy: invalid copy offset")
        # overlapping copies are byte-at-a-time by spec
        start = len(out) - off
        if off >= ln:
            out += out[start:start + ln]
        else:
            for i in range(ln):
                out.append(out[start + i])
    if len(out) != expected:
        raise SnappyError(
            f"snappy: length mismatch (got {len(out)}, expected {expected})")
    return bytes(out)


_MAX_LITERAL = 1 << 16


def compress(buf: bytes) -> bytes:
    """Literal-only snappy encoder (valid for any decoder)."""
    out = bytearray(_write_varint32(len(buf)))
    pos = 0
    n = len(buf)
    while pos < n:
        ln = min(_MAX_LITERAL, n - pos)
        if ln <= 60:
            out.append((ln - 1) << 2)
        elif ln <= 0x100:
            out.append(60 << 2)
            out += (ln - 1).to_bytes(1, "little")
        else:
            out.append(61 << 2)
            out += (ln - 1).to_bytes(2, "little")
        out += buf[pos:pos + ln]
        pos += ln
    return bytes(out)
