// RecordIO native kernels: crc32 + record-frame splitting.
//
// Capability parity with the reference's C++ recordio library (reference:
// paddle/fluid/recordio/{header,chunk,scanner,writer}.* — kMagicNumber
// header.h:23, chunk framing chunk.cc). The chunk header/IO orchestration
// lives in python (__init__.py); this file carries the byte-crunching hot
// path (checksum over chunk payloads, splitting a chunk payload into
// length-prefixed records) so scanning large files does not loop in
// python. Built lazily with g++ -O2 -shared; __init__.py falls back to
// pure python (zlib.crc32 + struct) when no compiler is available.
//
// Build: g++ -O2 -fPIC -shared -o librecordio.so native.cc

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// CRC-32 (IEEE 802.3, same polynomial as zlib.crc32) with a lazily built
// table — keeps the .so dependency-free.
static uint32_t g_table[256];
static bool g_table_ready = false;

static void build_table() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    g_table[i] = c;
  }
  g_table_ready = true;
}

uint32_t rio_crc32(const uint8_t* data, size_t n) {
  if (!g_table_ready) build_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) {
    c = g_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// Split a chunk payload (concatenated [u32-le length | bytes] frames) into
// (offset, length) pairs. Returns the record count, or -1 on a malformed
// payload (truncated frame / overflow), or -2 if there are more records
// than max_records.
long rio_split_records(const uint8_t* payload, size_t n, uint32_t* offsets,
                       uint32_t* lengths, size_t max_records) {
  size_t pos = 0;
  size_t count = 0;
  while (pos < n) {
    if (pos + 4 > n) return -1;
    uint32_t len;
    std::memcpy(&len, payload + pos, 4);  // little-endian hosts only (x86/ARM)
    pos += 4;
    if (pos + len > n) return -1;
    if (count >= max_records) return -2;
    offsets[count] = static_cast<uint32_t>(pos);
    lengths[count] = len;
    pos += len;
    ++count;
  }
  return static_cast<long>(count);
}

}  // extern "C"
