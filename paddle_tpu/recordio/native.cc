// RecordIO native kernels: crc32 + record-frame splitting.
//
// Capability parity with the reference's C++ recordio library (reference:
// paddle/fluid/recordio/{header,chunk,scanner,writer}.* — kMagicNumber
// header.h:23, chunk framing chunk.cc). The chunk header/IO orchestration
// lives in python (__init__.py); this file carries the byte-crunching hot
// path (checksum over chunk payloads, splitting a chunk payload into
// length-prefixed records) so scanning large files does not loop in
// python. Built lazily with g++ -O2 -shared; __init__.py falls back to
// pure python (zlib.crc32 + struct) when no compiler is available.
//
// Build: g++ -O2 -fPIC -shared -o librecordio.so native.cc

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// CRC-32 (IEEE 802.3, same polynomial as zlib.crc32) with a lazily built
// table — keeps the .so dependency-free.
static uint32_t g_table[256];
static bool g_table_ready = false;

static void build_table() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    }
    g_table[i] = c;
  }
  g_table_ready = true;
}

uint32_t rio_crc32(const uint8_t* data, size_t n) {
  if (!g_table_ready) build_table();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) {
    c = g_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// CRC-32C (Castagnoli) — the checksum the snappy framing format mandates.
static uint32_t g_ctable[256];
static bool g_ctable_ready = false;

static void build_ctable() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) {
      c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    }
    g_ctable[i] = c;
  }
  g_ctable_ready = true;
}

uint32_t rio_crc32c(const uint8_t* data, size_t n) {
  if (!g_ctable_ready) build_ctable();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) {
    c = g_ctable[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// Raw snappy codec — the per-byte hot path of the pure-python codec in
// snappy_codec.py, same greedy hash-table scheme as C snappy. The python
// layer keeps the framing/orchestration and falls back to its own
// implementation when this library is unavailable.
// ---------------------------------------------------------------------------

static size_t emit_varint(uint8_t* out, uint64_t v) {
  size_t i = 0;
  while (v >= 0x80) {
    out[i++] = static_cast<uint8_t>((v & 0x7F) | 0x80);
    v >>= 7;
  }
  out[i++] = static_cast<uint8_t>(v);
  return i;
}

static size_t emit_literal(uint8_t* out, const uint8_t* src, size_t len) {
  size_t o = 0;
  while (len) {
    size_t ln = len > 65536 ? 65536 : len;
    if (ln <= 60) {
      out[o++] = static_cast<uint8_t>((ln - 1) << 2);
    } else if (ln <= 256) {
      out[o++] = 60 << 2;
      out[o++] = static_cast<uint8_t>(ln - 1);
    } else {
      out[o++] = 61 << 2;
      out[o++] = static_cast<uint8_t>((ln - 1) & 0xFF);
      out[o++] = static_cast<uint8_t>(((ln - 1) >> 8) & 0xFF);
    }
    std::memcpy(out + o, src, ln);
    o += ln;
    src += ln;
    len -= ln;
  }
  return o;
}

static size_t emit_copy(uint8_t* out, size_t off, size_t len) {
  size_t o = 0;
  while (len >= 68) {  // long matches split into <=64-byte copies
    out[o++] = (59 << 2) | 2;
    out[o++] = static_cast<uint8_t>(off & 0xFF);
    out[o++] = static_cast<uint8_t>((off >> 8) & 0xFF);
    len -= 60;
  }
  if (len > 64) {
    out[o++] = (59 << 2) | 2;
    out[o++] = static_cast<uint8_t>(off & 0xFF);
    out[o++] = static_cast<uint8_t>((off >> 8) & 0xFF);
    len -= 60;
  }
  if (len >= 4 && len <= 11 && off < 2048) {
    out[o++] = static_cast<uint8_t>(((len - 4) << 2) | ((off >> 8) << 5) | 1);
    out[o++] = static_cast<uint8_t>(off & 0xFF);
  } else {
    out[o++] = static_cast<uint8_t>(((len - 1) << 2) | 2);
    out[o++] = static_cast<uint8_t>(off & 0xFF);
    out[o++] = static_cast<uint8_t>((off >> 8) & 0xFF);
  }
  return o;
}

// Greedy compress. `cap` must be >= 8 + n + 3*(n/65536 + 1) (literal-only
// worst case; copies never cost more than the literal bytes they replace).
// Returns the compressed length, or -1 if cap is insufficient.
long rio_snappy_compress(const uint8_t* in, size_t n, uint8_t* out,
                         size_t cap) {
  if (cap < 8 + n + 3 * (n / 65536 + 1)) return -1;
  size_t o = emit_varint(out, n);
  if (n < 4) {
    if (n) o += emit_literal(out + o, in, n);
    return static_cast<long>(o);
  }
  const int kShift = 32 - 14;  // 16384-entry table
  static thread_local int64_t table[1 << 14];
  for (size_t i = 0; i < (1u << 14); ++i) table[i] = -1;
  size_t pos = 0, lit = 0;
  const size_t limit = n - 3;
  while (pos < limit) {
    uint32_t cur;
    std::memcpy(&cur, in + pos, 4);
    uint32_t h = (cur * 0x1E35A7BDu) >> kShift;
    int64_t cand = table[h];
    table[h] = static_cast<int64_t>(pos);
    if (cand >= 0 && pos - static_cast<size_t>(cand) <= 0xFFFF) {
      uint32_t cv;
      std::memcpy(&cv, in + cand, 4);
      if (cv == cur) {
        size_t m = 4;  // overlap-extending match is legal in snappy
        while (pos + m < n && in[cand + m] == in[pos + m]) ++m;
        o += emit_literal(out + o, in + lit, pos - lit);
        o += emit_copy(out + o, pos - static_cast<size_t>(cand), m);
        pos += m;
        lit = pos;
        continue;
      }
    }
    ++pos;
  }
  o += emit_literal(out + o, in + lit, n - lit);
  return static_cast<long>(o);
}

// Full raw-snappy decoder. Returns the decompressed length, -1 on a
// malformed stream, or -2 if `cap` is smaller than the declared length.
long rio_snappy_decompress(const uint8_t* in, size_t n, uint8_t* out,
                           size_t cap) {
  uint64_t expected = 0;
  int shift = 0;
  size_t pos = 0;
  while (true) {
    if (pos >= n) return -1;
    uint8_t b = in[pos++];
    expected |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 32) return -1;
  }
  if (expected > cap) return -2;
  size_t o = 0;
  while (pos < n) {
    uint8_t tag = in[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) {  // literal
      size_t ln = tag >> 2;
      if (ln >= 60) {
        size_t nb = ln - 59;
        if (pos + nb > n) return -1;
        ln = 0;
        for (size_t i = 0; i < nb; ++i) ln |= static_cast<size_t>(in[pos + i]) << (8 * i);
        pos += nb;
      }
      ++ln;
      if (pos + ln > n || o + ln > cap) return -1;
      std::memcpy(out + o, in + pos, ln);
      o += ln;
      pos += ln;
      continue;
    }
    size_t ln, off;
    if (kind == 1) {
      ln = ((tag >> 2) & 7) + 4;
      if (pos >= n) return -1;
      off = (static_cast<size_t>(tag >> 5) << 8) | in[pos];
      pos += 1;
    } else if (kind == 2) {
      ln = (tag >> 2) + 1;
      if (pos + 2 > n) return -1;
      off = in[pos] | (static_cast<size_t>(in[pos + 1]) << 8);
      pos += 2;
    } else {
      ln = (tag >> 2) + 1;
      if (pos + 4 > n) return -1;
      off = 0;
      for (int i = 0; i < 4; ++i) off |= static_cast<size_t>(in[pos + i]) << (8 * i);
      pos += 4;
    }
    if (off == 0 || off > o || o + ln > cap) return -1;
    size_t start = o - off;
    for (size_t i = 0; i < ln; ++i) out[o + i] = out[start + i];  // overlap-safe
    o += ln;
  }
  if (o != expected) return -1;
  return static_cast<long>(o);
}

// Split a chunk payload (concatenated [u32-le length | bytes] frames) into
// (offset, length) pairs. Returns the record count, or -1 on a malformed
// payload (truncated frame / overflow), or -2 if there are more records
// than max_records.
long rio_split_records(const uint8_t* payload, size_t n, uint32_t* offsets,
                       uint32_t* lengths, size_t max_records) {
  size_t pos = 0;
  size_t count = 0;
  while (pos < n) {
    if (pos + 4 > n) return -1;
    uint32_t len;
    std::memcpy(&len, payload + pos, 4);  // little-endian hosts only (x86/ARM)
    pos += 4;
    if (pos + len > n) return -1;
    if (count >= max_records) return -2;
    offsets[count] = static_cast<uint32_t>(pos);
    lengths[count] = len;
    pos += len;
    ++count;
  }
  return static_cast<long>(count);
}

}  // extern "C"
