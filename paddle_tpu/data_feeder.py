"""DataFeeder: python rows -> padded device-ready feed dicts.

Capability parity with reference python/paddle/fluid/data_feeder.py:81
(`DataFeeder.feed` builds LoDTensors from nested lists). TPU-native: LoD
sequences become (padded dense array, lengths) pairs which the executor feeds
as `name` + `name@SEQLEN`.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .core import ir, types


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = []
        program = program or ir.default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable, pad_to: int = 0):
        """`iterable` is a batch: list of rows, each row a tuple with one
        entry per feed var. Returns {name: array | (array, lengths)}."""
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            col = [row[i] for row in rows]
            dtype = types.np_dtype(var.dtype)
            if var.lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                shape = [d for d in var.shape if d != -1]
                if arr.ndim == 1 and len(shape) > 0 and int(np.prod(shape)) > 1:
                    arr = arr.reshape([len(rows)] + shape)
                elif arr.ndim == len(shape):  # missing batch dim broadcuing
                    pass
                # classification labels: [N] -> [N, 1] when var declared 2-D
                if arr.ndim == 1 and len(var.shape) == 2 and var.shape[-1] == 1:
                    arr = arr.reshape(-1, 1)
                out[var.name] = arr
            elif var.lod_level >= 2:
                # nested sequences: each sample is a list of sequences
                outer = np.array([len(doc) for doc in col], np.int32)
                S = max(1, int(outer.max()))
                inner = np.zeros((len(col), S), np.int32)
                T = 1
                feat = None
                for b, doc in enumerate(col):
                    for s_i, seq in enumerate(doc):
                        a = np.asarray(seq, dtype=dtype)
                        inner[b, s_i] = a.shape[0]
                        T = max(T, a.shape[0])
                        if feat is None and a.ndim > 1:
                            feat = list(a.shape[1:])
                if pad_to:
                    T = max(T, pad_to)   # shape-stable steps, as level 1
                feat = feat or ([1] if len(var.shape) >= 4
                                and var.shape[-1] == 1 else [])
                padded = np.zeros([len(col), S, T] + feat, dtype=dtype)
                for b, doc in enumerate(col):
                    for s_i, seq in enumerate(doc):
                        a = np.asarray(seq, dtype=dtype)
                        if a.ndim == 1 and feat == [1]:
                            a = a.reshape(-1, 1)
                        padded[b, s_i, : a.shape[0]] = a
                out[var.name] = (padded, (outer, inner))
            else:
                lens = np.array([len(s) for s in col], np.int32)
                maxlen = max(int(lens.max()), 1)
                if pad_to:
                    maxlen = max(maxlen, pad_to)
                first = np.asarray(col[0], dtype=dtype)
                feat = list(first.shape[1:])
                padded = np.zeros([len(col), maxlen] + feat, dtype=dtype)
                for b, seq in enumerate(col):
                    s = np.asarray(seq, dtype=dtype)
                    if s.ndim == 1 and len(var.shape) >= 3 and var.shape[-1] == 1:
                        s = s.reshape(-1, 1)
                    padded[b, : len(seq)] = s
                out[var.name] = (padded, lens)
        return out
