"""fluid-torrent: disaggregated LLM serving (see docs/TORRENT.md).

Real generative traffic has two phases with opposite hardware appetites:
prefill is compute-bound (one big causal-attention pass over the
prompt), decode is memory-bound (one whole-cache read per token).
Co-locating them on one chip makes TTFT and tokens/s fight — a long
prompt's prefill stalls every decoding sequence behind it. fluid-torrent
splits the phases across replica POOLS:

- a **prefill replica** runs the prompt's prefill step only
  (`InferenceServer.submit_prefill`), extracts the prompt's paged KV
  block rows, and streams them over the wire to a decode replica;
- a **decode replica** injects the rows at its own block ids
  (`InferenceServer.submit_prefilled`) and runs the rest of the
  generation — pure decode steps, the batch never stalls on a prefill.

The wire transfer (`torrent.stream`) reuses two proven idioms: the
fluid-wire int8 tensor codec for block payloads (KV blocks tolerate the
same quantization the EQuARX-style gradient path does — and an
int8-resident cache ships its bytes verbatim, losslessly), and the
fluid-haven `UpdateLog` seq-numbered-record window for ordered,
RESUMABLE transfer — a torn connection re-streams from the last acked
seq, the receiver dedups by seq, and a superseded transfer is detected
by nonce.

`fleet.FleetRouter.generate_torrent` orchestrates the pair: prefill
stays least-loaded with full retry/failover; the generating sequence
pins to its decode replica (session affinity keyed on sequence id,
released on EOS/cancel/replica death). Because decoding is greedy and
deterministic, a dead decode replica costs a re-prefill, never a wrong
token.
"""

from __future__ import annotations

from .prefill import prefill_and_stream  # noqa: F401
from .stream import (RECORD_BEGIN, RECORD_BLOCK,  # noqa: F401
                     RECORD_COMMIT, KVStreamReceiver, KVStreamSender,
                     build_records)
