"""fluid-torrent prefill driver: local prefill -> wire stream.

The prefill replica's half of a disaggregated generation, run by its
fleet replica's `torrent_prefill` handler: run the prompt through this
server's prefill-only path, then pump the extracted KV payload through a
KVStreamSender to the decode replica the router pinned. Returns the
summary the router needs to finish orchestrating (first token, local
TTFT, bytes shipped).

Failure split (the router's cue): serve-side errors (backpressure, bad
request) raise their own ServeError types; a transfer that cannot reach
or resume on the decode replica raises KVTransferError — the router
releases the pin and re-prefills against a fresh decode replica, which
is safe because greedy decoding is deterministic.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

from .. import flags as _flags
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from ..serve.errors import ServeError
from .stream import KVStreamSender

_m_prefills = _metrics.counter(
    "torrent_prefills_total",
    "disaggregated prefill halves by outcome, per model")


def prefill_and_stream(server, model: str, prompt, max_new: int,
                       seq_id: str, send: Callable[[list], int],
                       deadline_ms: Optional[float] = None,
                       trace: Optional[dict] = None,
                       max_records: int = 16,
                       max_retries: int = 3) -> dict:
    """Run the prefill half on `server` and stream the KV payload via
    `send` (fleet-provided, one batch per call). Returns
    {first_token, ttft_us, prompt_len, n_blocks, records, bytes,
    stream_us, nonce}."""
    cm = (_xray.span("torrent:prefill", cat="torrent", model=model,
                     seq=seq_id)
          if _flags.get_flag("observe") else contextlib.nullcontext())
    t0 = time.monotonic()
    with cm:
        try:
            r = server.submit_prefill(
                model, prompt, deadline_ms=deadline_ms).result()
            sender = KVStreamSender(
                model, seq_id, prompt, r.tokens[0], max_new, r.kv,
                trace=trace)
            sender.pump(send, max_records=max_records,
                        max_retries=max_retries)
        except ServeError as e:
            _m_prefills.inc(model=model, outcome=type(e).__name__)
            raise
        _m_prefills.inc(model=model, outcome="ok")
        return {"first_token": int(r.tokens[0]),
                "ttft_us": float(r.ttft_us),
                "prompt_len": int(r.kv["prompt_len"]),
                "n_blocks": int(r.kv["n_blocks"]),
                "records": sender.total_records,
                "bytes": sender.bytes_sent,
                "stream_us": (time.monotonic() - t0) * 1e6,
                "nonce": sender.nonce}
