"""fluid-torrent KV streaming: ordered, resumable, dedup-by-seq.

A transfer is a short seq-numbered record stream:

    kv_begin   — transfer metadata: model, seq_id, nonce, prompt, the
                 prefill's first token, block geometry, kv_dtype, and
                 the originating request's trace context
    kv_block   — one cache var's one block row. fp32 residency encodes
                 the row with the wire int8 codec (lossy, ~4x smaller);
                 int8 residency ships the already-quantized bytes plus
                 the per-block scale VERBATIM (lossless)
    kv_commit  — all rows sent: the receiver assembles the payload and
                 admits it into its decode engine

The sender drives a haven `UpdateLog`: every record is appended once,
`batch()` always re-returns everything past the acked watermark, and
`ack()` trims — so after a torn connection the sender just batches
again and the stream resumes from the last acked seq. The receiver
applies records in seq order, drops duplicates (a lost ack costs bytes,
never correctness), and replies its contiguous-applied watermark.

Failure taxonomy: a transport error mid-stream is retriable against the
SAME receiver (resume-from-watermark); a receiver that lost its staging
state (process restart) or saw a NEWER nonce for the seq_id raises
KVTransferError — the router's cue to re-prefill somewhere else.

Transport-agnostic: the sender takes a `send(records) -> acked_seq`
callable and the receiver exposes `handle(records) -> reply`; the fleet
tier wires them over its RPC frames (fleet/replica.py `torrent_kv`).
"""

from __future__ import annotations

import threading
import uuid
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from ..haven.log import UpdateLog
from ..observe import metrics as _metrics
from ..serve.errors import KVTransferError
from ..wire import codec as _codec

RECORD_BEGIN = "kv_begin"
RECORD_BLOCK = "kv_block"
RECORD_COMMIT = "kv_commit"


def build_records(model: str, seq_id: str, nonce: str, prompt,
                  first_token: int, max_new: int, kv: dict,
                  trace: Optional[dict] = None):
    """Flatten a prefill's extracted KV payload (serve/decode.py
    `_extract_kv` shape) into the transfer's (cmd, payload) records.
    Block rows are keyed (var, ordinal) so the receiver can reassemble
    position order regardless of arrival batching."""
    kv_dtype = str(kv.get("kv_dtype", "fp32"))
    n_blocks = int(kv["n_blocks"])
    cache_vars = sorted(kv["cache"])
    recs = [(RECORD_BEGIN, {
        "model": model, "seq_id": seq_id, "nonce": nonce,
        "prompt": [int(t) for t in prompt],
        "first_token": int(first_token), "max_new": int(max_new),
        "prompt_len": int(kv["prompt_len"]), "n_blocks": n_blocks,
        "cache_vars": cache_vars, "kv_dtype": kv_dtype,
        "trace": trace,
    })]
    scales = kv.get("scales") or {}
    for cname in cache_vars:
        rows = np.asarray(kv["cache"][cname])
        for j in range(n_blocks):
            payload = {"seq_id": seq_id, "nonce": nonce, "var": cname,
                       "ordinal": j}
            if kv_dtype == "int8":
                # already quantized on-chip: ship the bytes + the block
                # scale verbatim — the decode replica's residency is
                # bit-identical to the prefill replica's
                payload["data"] = np.array(rows[j])
                payload["scale"] = float(np.asarray(scales[cname])[j])
            else:
                payload["data"] = _codec.encode_tensor(
                    rows[j], "int8", name=f"{cname}[{j}]")
            recs.append((RECORD_BLOCK, payload))
    recs.append((RECORD_COMMIT, {
        "seq_id": seq_id, "nonce": nonce, "n_records": len(recs) + 1}))
    return recs


def _record_nbytes(cmd: str, payload: dict) -> int:
    if cmd != RECORD_BLOCK:
        return 0
    n = _codec.payload_nbytes(payload["data"])
    if "scale" in payload:
        n += 4
    return n


class KVStreamSender:
    """One transfer's sending half, bound to one UpdateLog.

    Appends every record up front (the window must cover the whole
    transfer — KV streams are short; a model whose transfer outgrows the
    window should raise it, not block), then `pump()` drives
    batch→send→ack to completion with resume-from-watermark on transport
    errors."""

    def __init__(self, model: str, seq_id: str, prompt, first_token: int,
                 max_new: int, kv: dict, nonce: Optional[str] = None,
                 trace: Optional[dict] = None, window: int = 4096):
        self.model = model
        self.seq_id = seq_id
        self.nonce = nonce or uuid.uuid4().hex[:12]
        records = build_records(model, seq_id, self.nonce, prompt,
                                first_token, max_new, kv, trace=trace)
        if len(records) > window:
            raise KVTransferError(
                f"transfer of {len(records)} records exceeds the "
                f"UpdateLog window {window} — raise the window")
        self._log = UpdateLog(window=window)
        # a transfer needs no snapshot phase: clear the fresh log's
        # resync flag so lag() reads the true backlog
        self._log.rebase(0)
        for cmd, payload in records:
            self._log.append(cmd, payload)
        self.total_records = len(records)
        self.bytes_sent = 0
        self.resumes = 0
        self._m_bytes = _metrics.counter(
            "torrent_kv_transfer_bytes_total",
            "KV block bytes shipped prefill->decode (retransmits "
            "included), per model")
        self._m_resumes = _metrics.counter(
            "torrent_kv_stream_resumes_total",
            "KV streams resumed from the acked watermark after a "
            "transport error, per model")

    @property
    def done(self) -> bool:
        return self._log.acked_seq >= self._log.head_seq

    def pump(self, send: Callable[[list], int], max_records: int = 16,
             max_retries: int = 3):
        """Drive the transfer to completion. `send` ships one batch of
        (seq, cmd, payload, trace) records and returns the receiver's
        acked watermark; it raises on transport failure. Transport
        errors resume from the watermark (`batch()` re-returns the
        unacked tail) up to `max_retries` consecutive times, then
        surface as KVTransferError. A watermark that refuses to advance
        (receiver superseded/reset without raising) also fails the
        transfer — progress is the invariant, not politeness."""
        failures = 0
        while not self.done:
            batch = self._log.batch(max_records)
            try:
                acked = int(send(batch))
            except KVTransferError:
                # the receiver itself rejected the transfer (superseded
                # nonce, lost staging): resuming cannot help
                raise
            except Exception as e:          # noqa: BLE001 — transport
                failures += 1
                self.resumes += 1
                self._m_resumes.inc(model=self.model)
                if failures > max_retries:
                    raise KVTransferError(
                        f"KV stream for seq {self.seq_id!r} failed "
                        f"{failures} times at seq "
                        f"{self._log.acked_seq}/{self._log.head_seq}: "
                        f"{e!r}") from e
                continue
            nbytes = sum(_record_nbytes(c, p) for _s, c, p, _t in batch)
            self.bytes_sent += nbytes
            if nbytes:
                self._m_bytes.inc(nbytes, model=self.model)
            if acked <= self._log.acked_seq:
                raise KVTransferError(
                    f"KV stream for seq {self.seq_id!r} stalled: "
                    f"receiver acked {acked}, watermark already at "
                    f"{self._log.acked_seq}")
            failures = 0
            self._log.ack(acked)


class _Staging:
    """One in-flight transfer on the receiving side."""

    __slots__ = ("seq_id", "nonce", "meta", "blocks", "applied_seq",
                 "committed")

    def __init__(self, seq_id, nonce, meta, applied_seq):
        self.seq_id = seq_id
        self.nonce = nonce
        self.meta = meta
        # var -> ordinal -> (data, scale|None)
        self.blocks: Dict[str, Dict[int, tuple]] = {}
        self.applied_seq = applied_seq
        self.committed = False


class KVStreamReceiver:
    """The decode replica's staging table: applies record batches in seq
    order (dedup by seq), assembles the KV payload at commit, and admits
    it via the injected `admit` callable (the fleet tier passes
    `InferenceServer.submit_prefilled`). A NEWER nonce for a seq_id
    supersedes the old staging — the router's re-prefill retry path —
    and batches still arriving for the old nonce get KVTransferError."""

    def __init__(self, admit: Callable[..., Future]):
        self._admit = admit
        self._lock = threading.Lock()
        self._staging: Dict[str, _Staging] = {}  # guarded_by: self._lock
        self._futures: Dict[str, Future] = {}    # guarded_by: self._lock
        self._m_blocks = _metrics.counter(
            "torrent_kv_blocks_streamed_total",
            "KV cache block rows applied from the wire, per model")

    def handle(self, records: List) -> dict:
        """Apply one batch; returns {"acked": <contiguous watermark>}.
        Records below the watermark are duplicates (dropped); a gap
        stops the batch (the sender re-streams from the reply)."""
        admit_now = None
        with self._lock:
            acked = 0
            for rec in records:
                seq, cmd, payload = rec[0], rec[1], rec[2]
                seq = int(seq)
                if cmd == RECORD_BEGIN:
                    st = self._staging.get(payload["seq_id"])
                    if st is not None and st.nonce == payload["nonce"]:
                        acked = st.applied_seq   # duplicate begin
                        continue
                    # fresh (or superseding) transfer
                    st = _Staging(payload["seq_id"], payload["nonce"],
                                  payload, seq)
                    self._staging[payload["seq_id"]] = st
                    acked = seq
                    continue
                st = self._staging.get(payload.get("seq_id"))
                if st is None or st.nonce != payload.get("nonce"):
                    raise KVTransferError(
                        f"transfer {payload.get('seq_id')!r} nonce "
                        f"{payload.get('nonce')!r} has no staging here "
                        f"(superseded or receiver restarted) — "
                        f"re-prefill")
                if seq <= st.applied_seq:
                    acked = st.applied_seq       # duplicate
                    continue
                if seq != st.applied_seq + 1:
                    acked = st.applied_seq       # gap: stop, re-stream
                    break
                if cmd == RECORD_BLOCK:
                    st.blocks.setdefault(payload["var"], {})[
                        int(payload["ordinal"])] = (
                        payload["data"], payload.get("scale"))
                    self._m_blocks.inc(model=st.meta["model"])
                elif cmd == RECORD_COMMIT:
                    admit_now = st
                else:
                    raise KVTransferError(
                        f"unknown KV stream record kind {cmd!r}")
                st.applied_seq = seq
                acked = seq
        if admit_now is not None:
            self._commit(admit_now)
        return {"acked": acked}

    def _commit(self, st: _Staging):
        """Assemble the staged rows into the serve-layer payload shape
        and admit. Runs outside the staging lock — admit() may block on
        the engine's admission queue."""
        meta = st.meta
        n_blocks = int(meta["n_blocks"])
        kv_dtype = str(meta["kv_dtype"])
        cache: Dict[str, np.ndarray] = {}
        scales: Dict[str, np.ndarray] = {}
        for cname in meta["cache_vars"]:
            got = st.blocks.get(cname, {})
            missing = [j for j in range(n_blocks) if j not in got]
            if missing:
                raise KVTransferError(
                    f"transfer {st.seq_id!r} committed with missing "
                    f"blocks {missing} for {cname!r}")
            if kv_dtype == "int8":
                cache[cname] = np.stack(
                    [np.asarray(got[j][0]) for j in range(n_blocks)])
                scales[cname] = np.array(
                    [float(got[j][1]) for j in range(n_blocks)],
                    np.float32)
            else:
                cache[cname] = np.stack(
                    [_codec.maybe_decode(got[j][0])
                     for j in range(n_blocks)])
        kv = {"cache": cache, "prompt_len": int(meta["prompt_len"]),
              "n_blocks": n_blocks, "kv_dtype": kv_dtype}
        if kv_dtype == "int8":
            kv["scales"] = scales
        fut = self._admit(
            meta["model"], meta["prompt"], meta["first_token"], kv,
            meta["max_new"], meta.get("trace"))
        with self._lock:
            st.committed = True
            self._futures[st.seq_id] = fut

    def future(self, seq_id: str) -> Future:
        """The committed generation's Future (KVTransferError when no
        transfer for seq_id committed here)."""
        with self._lock:
            fut = self._futures.get(seq_id)
        if fut is None:
            raise KVTransferError(
                f"no committed generation for seq {seq_id!r} on this "
                f"replica")
        return fut

    def release(self, seq_id: str):
        """Drop a transfer's staging and future (EOS collected, or the
        router released the session)."""
        with self._lock:
            self._staging.pop(seq_id, None)
            self._futures.pop(seq_id, None)

    def stats(self) -> dict:
        with self._lock:
            return {"staging": len(self._staging),
                    "futures": len(self._futures)}
