"""Python-side metric accumulators (reference:
python/paddle/fluid/metrics.py — MetricBase, CompositeMetric, Accuracy,
ChunkEvaluator, EditDistance, Auc)."""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for attr, v in list(self.__dict__.items()):
            if attr.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, attr, 0)
            elif isinstance(v, (np.ndarray,)):
                setattr(self, attr, np.zeros_like(v))

    def update(self, *a, **kw):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy metric")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data updated into EditDistance metric")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """Host-side streaming AUC over threshold buckets."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
            else preds.reshape(-1)
        idx = np.clip((pos_prob * self._num_thresholds).astype(int), 0,
                      self._num_thresholds)
        for i, lab in zip(idx, labels):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / max(tp[-1], 1.0)
        fpr = fp / max(fp[-1], 1.0)
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))


class Precision(MetricBase):
    """Binary precision tp/(tp+fp) (reference metrics.py Precision):
    update with sigmoid scores (rounded at 0.5) and {0,1} labels."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall tp/(tp+fn) (reference metrics.py Recall)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class DetectionMAP(MetricBase):
    """Accumulator over per-batch mAP values produced by
    layers.detection_map — EXACT reference semantics (metrics.py
    DetectionMAP.update accumulates the bare value and divides by the
    accumulated weight, so with the documented usage weight=batch_size the
    result is sum(batch_mAP)/sum(batch_size), NOT a weighted mean; ported
    scripts get the reference's numbers). Pass weight=1 per batch for a
    plain mean."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0])
        self.weight += float(np.asarray(weight).reshape(-1)[0])

    def eval(self):
        if self.weight == 0:
            raise ValueError("DetectionMAP: no batches accumulated")
        return self.value / self.weight
