"""Gradient clipping (reference: python/paddle/fluid/clip.py):
ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
GradientClipByGlobalNorm — applied between backward and the update ops."""

from __future__ import annotations

from .core import ir


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def append_clip_op(self, block, grad_name):
        block.append_op("clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max})


def error_clip_callback(block, context):
    pass  # per-op error clip hooks are applied via ErrorClipByValue directly


class BaseGradientClipAttr:
    def _create_operators(self, param, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=f"{grad.name}@clip", shape=grad.shape,
                               dtype=grad.dtype, stop_gradient=True)
        block.append_op("clip", inputs={"X": [grad.name]},
                        outputs={"Out": [out.name]},
                        attrs={"min": self.min, "max": self.max})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(name=f"{grad.name}@clip", shape=grad.shape,
                               dtype=grad.dtype, stop_gradient=True)
        block.append_op("clip_by_norm", inputs={"X": [grad.name]},
                        outputs={"Out": [out.name]},
                        attrs={"max_norm": self.clip_norm})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all gradients by clip_norm/max(global_norm, clip_norm)
    (reference clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    @staticmethod
    def apply(params_grads, clip_norm):
        from .layers import tensor as lt, ops as lops, nn as lnn
        from .layer_helper import LayerHelper
        if not params_grads:
            return params_grads
        block = params_grads[0][1].block
        sq_sums = []
        for p, g in params_grads:
            sq = block.create_var(name=f"{g.name}@sq", shape=(1,),
                                  dtype=g.dtype, stop_gradient=True)
            block.append_op("square", inputs={"X": [g.name]},
                            outputs={"Out": [f"{g.name}@sq_full"]})
            block.create_var(name=f"{g.name}@sq_full", shape=g.shape,
                             dtype=g.dtype, stop_gradient=True)
            block.append_op("reduce_sum", inputs={"X": [f"{g.name}@sq_full"]},
                            outputs={"Out": [sq.name]},
                            attrs={"reduce_all": True, "keep_dim": False})
            sq_sums.append(sq.name)
        gnorm_sq = block.create_var(name="@global_norm_sq@" + sq_sums[0],
                                    shape=(1,), dtype="float32", stop_gradient=True)
        block.append_op("sum", inputs={"X": sq_sums}, outputs={"Out": [gnorm_sq.name]})
        gnorm = block.create_var(name=gnorm_sq.name + "@sqrt", shape=(1,),
                                 dtype="float32", stop_gradient=True)
        block.append_op("sqrt", inputs={"X": [gnorm_sq.name]},
                        outputs={"Out": [gnorm.name]})
        # scale = clip_norm / max(gnorm, clip_norm)
        denom = block.create_var(name=gnorm.name + "@max", shape=(1,),
                                 dtype="float32", stop_gradient=True)
        cn = block.create_var(name=gnorm.name + "@cn", shape=(1,),
                              dtype="float32", stop_gradient=True)
        block.append_op("fill_constant", outputs={"Out": [cn.name]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": float(clip_norm)})
        block.append_op("elementwise_max", inputs={"X": [gnorm.name], "Y": [cn.name]},
                        outputs={"Out": [denom.name]}, attrs={"axis": -1})
        scale = block.create_var(name=gnorm.name + "@scale", shape=(1,),
                                 dtype="float32", stop_gradient=True)
        block.append_op("elementwise_div", inputs={"X": [cn.name], "Y": [denom.name]},
                        outputs={"Out": [scale.name]}, attrs={"axis": -1})
        out = []
        for p, g in params_grads:
            ng = block.create_var(name=f"{g.name}@gclip", shape=g.shape,
                                  dtype=g.dtype, stop_gradient=True)
            block.append_op("elementwise_mul", inputs={"X": [g.name], "Y": [scale.name]},
                            outputs={"Out": [ng.name]}, attrs={"axis": -1})
            out.append((p, block.vars[ng.name]))
        return out

    def _create_operators(self, param, grad):
        raise RuntimeError("use GradientClipByGlobalNorm.apply / set_gradient_clip")


_global_clip = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip


def append_gradient_clip_ops(params_grads):
    global _global_clip
    if isinstance(_global_clip, GradientClipByGlobalNorm):
        return GradientClipByGlobalNorm.apply(params_grads, _global_clip.clip_norm)
    out = []
    for p, g in params_grads:
        if g is None:
            out.append((p, g))
            continue
        clip_attr = getattr(p, "gradient_clip", None) or _global_clip
        if clip_attr is None:
            out.append((p, g))
        else:
            out.append(clip_attr._create_operators(p, g))
    return out
