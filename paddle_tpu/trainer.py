"""High-level Trainer / checkpointing.

Capability parity with reference python/paddle/fluid/trainer.py: event
classes :38-92, `Trainer` :167 (builds train program from a train_func,
transpiles from env, trains by executor or ParallelExecutor :439-529),
`CheckpointConfig` :98 and the serial-dir checkpoint protocol
(`save_checkpoint` :637 / `load_checkpoint` :737, `_SUCCESS` marker
`_write_success` :1186, rotation `_scroll_delete` :1164).
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Callable, List, Optional

import numpy as np

from . import ark as _ark
from . import flags as _flags
from . import io as fluid_io
from .observe import health as _obs_health
from .observe import metrics as _obs_metrics
from .observe import tracer as _obs_tracer
from . import unique_name
from .core import ir
from .core.executor import Executor, Scope, TPUPlace, global_scope
from .data_feeder import DataFeeder
from .parallel.parallel_executor import BuildStrategy, ParallelExecutor


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference trainer.py:98 — serial checkpoint dirs with rotation."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoint")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(epoch_interval, 1)
        self.step_interval = max(step_interval, 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


SERIAL_PREFIX = "checkpoint_"
TRAINER_ARGS_NAME = "trainer_args.json"
SUCCESS_MARK = "_SUCCESS"


def _serial_dir(root, serial):
    return os.path.join(root, f"{SERIAL_PREFIX}{serial}")


def get_latest_checkpoint_serial(checkpoint_dir) -> int:
    """Highest serial with a _SUCCESS marker (reference :1203)."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return -1
    best = -1
    for name in os.listdir(checkpoint_dir):
        if not name.startswith(SERIAL_PREFIX):
            continue
        try:
            serial = int(name[len(SERIAL_PREFIX):])
        except ValueError:
            continue
        if os.path.exists(os.path.join(checkpoint_dir, name, SUCCESS_MARK)):
            best = max(best, serial)
    return best


def save_checkpoint(executor, checkpoint_dir, trainer_id, main_program,
                    trainer_args=None, max_num_checkpoints=3, scope=None):
    """Write a new serial dir: params + trainer args + _SUCCESS, then rotate
    (reference :637, :1164, :1186)."""
    import json
    serial = get_latest_checkpoint_serial(checkpoint_dir) + 1
    cur = _serial_dir(checkpoint_dir, serial)
    os.makedirs(cur, exist_ok=True)
    fluid_io.save_persistables(executor, cur, main_program, scope=scope)
    if trainer_args is not None:
        with open(os.path.join(cur, f"trainer_{trainer_id}_{TRAINER_ARGS_NAME}"),
                  "w") as f:
            json.dump(trainer_args, f)
    with open(os.path.join(cur, SUCCESS_MARK), "w") as f:
        f.write("")
    # rotate old serials
    serials = sorted(
        int(n[len(SERIAL_PREFIX):]) for n in os.listdir(checkpoint_dir)
        if n.startswith(SERIAL_PREFIX) and n[len(SERIAL_PREFIX):].isdigit())
    for s in serials[: max(0, len(serials) - max_num_checkpoints)]:
        shutil.rmtree(_serial_dir(checkpoint_dir, s), ignore_errors=True)
    return serial


def load_checkpoint(executor, checkpoint_dir, serial, main_program,
                    trainer_id=0, scope=None):
    """Restore params (+ returns trainer args if present) from a serial dir
    (reference :737)."""
    import json
    if serial is None or serial < 0:
        raise ValueError(f"no valid checkpoint serial: {serial}")
    cur = _serial_dir(checkpoint_dir, serial)
    if not os.path.exists(os.path.join(cur, SUCCESS_MARK)):
        raise RuntimeError(f"checkpoint {cur} has no {SUCCESS_MARK} marker")
    fluid_io.load_persistables(executor, cur, main_program, scope=scope)
    args_path = os.path.join(cur, f"trainer_{trainer_id}_{TRAINER_ARGS_NAME}")
    if os.path.exists(args_path):
        with open(args_path) as f:
            return json.load(f)
    return None


class Trainer:
    """reference trainer.py:167.

    train_func() -> (loss, [metrics...]) builds the model into the trainer's
    programs; optimizer_func() -> Optimizer. parallel=True trains through
    ParallelExecutor over the whole mesh.
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 param_path=None, place=None, parallel=False,
                 checkpoint_config: Optional[CheckpointConfig] = None,
                 pulse_port: Optional[int] = None):
        self.place = place or TPUPlace(0)
        # fluid-pulse opt-in: expose this trainer process's live health
        # plane (/metrics /healthz /status ...). Requires the observe
        # flag — start_pulse refuses otherwise, by contract.
        self.pulse_port = None
        if pulse_port is not None:
            from .observe import pulse as _obs_pulse
            self.pulse_port = _obs_pulse.start_pulse(pulse_port)
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = ir.Program()
        self.train_program = ir.Program()
        with ir.program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.loss = out[0]
                self.metrics = list(out[1]) if len(out) > 1 and \
                    isinstance(out[1], (list, tuple)) else list(out[1:])
            else:
                self.loss = out
                self.metrics = []
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)
        self.test_program = self.train_program.clone(for_test=True)

        self.exe = Executor(self.place)
        self.exe.run(self.startup_program, scope=self.scope)
        if param_path:
            fluid_io.load_persistables(self.exe, param_path,
                                       self.train_program, scope=self.scope)
        if self.checkpoint_cfg:
            serial = get_latest_checkpoint_serial(
                self.checkpoint_cfg.checkpoint_dir)
            if serial >= 0:
                args = load_checkpoint(self.exe,
                                       self.checkpoint_cfg.checkpoint_dir,
                                       serial, self.train_program,
                                       scope=self.scope)
                if args:
                    self.checkpoint_cfg.epoch_id = args.get("epoch_id", 0)
                    self.checkpoint_cfg.step_id = args.get("step_id", 0)
        self._pe = None
        # prepared-step handles per fetch set (Executor.prepare): the
        # train loop's per-step host dispatch skips the key rebuild and
        # scope state gather entirely
        self._prepared = {}

    def _executor_run(self, feed, fetch_list):
        if self.parallel:
            if self._pe is None:
                self._pe = ParallelExecutor(main_program=self.train_program,
                                            loss_name=self.loss.name,
                                            scope=self.scope)
            return self._pe.run(fetch_list=fetch_list, feed=feed)
        from . import flags as _flags
        key = tuple(f.name if isinstance(f, ir.Variable) else str(f)
                    for f in fetch_list)
        # re-prepare when the program mutates or a flag flips — the same
        # invalidation Executor.run()'s memo provides, so holding the
        # handle never changes behavior vs the run() path
        ver = (self.train_program._version, _flags.version(),
               self.exe._check_nan_inf)
        hit = self._prepared.get(key)
        if hit is None or hit[1] != ver:
            hit = (self.exe.prepare(self.train_program,
                                    fetch_list=fetch_list,
                                    scope=self.scope), ver)
            self._prepared[key] = hit
        return hit[0].run(feed)

    # -- ark durable checkpoints (fluid-ark) ------------------------------
    def _ark_state(self):
        """(arrays, rng) for an ark checkpoint: every persistable var of
        the train program — parameters AND optimizer slot vars — plus the
        executor PRNG stream state (the per-program run counter that
        derives each step's fold_in key, and the unseeded-stream
        ordinal), so a resumed run draws the SAME per-step keys the
        uninterrupted run would have."""
        arrays = {}
        for v in fluid_io._collect(self.train_program,
                                   fluid_io._is_persistable):
            val = self.scope.find_var(v.name)
            if val is not None:
                arrays[v.name] = np.asarray(val)
        uid = self.train_program._uid
        rng = {"train_runs": int(self.exe._run_counts.get(uid, 0)),
               "stream": int(self.exe._prog_order.get(uid, -1))}
        return arrays, rng

    def _ark_restore(self, arrays, manifest):
        for v in fluid_io._collect(self.train_program,
                                   fluid_io._is_persistable):
            if v.name in arrays:
                self.scope.set_var(v.name, arrays[v.name])
        rng = manifest.get("rng", {})
        uid = self.train_program._uid
        if "train_runs" in rng:
            self.exe._run_counts[uid] = int(rng["train_runs"])
        stream = int(rng.get("stream", -1))
        if stream >= 0:
            # the rebuilt program gets the ORIGINAL run's stream ordinal
            # (unseeded-program PRNG keys mix it in); keep the monotone
            # source ahead so no later program collides with it
            self.exe._prog_order[uid] = stream
            self.exe._next_stream = max(self.exe._next_stream, stream + 1)

    def _ark_save(self, cfg, epoch_id, step_id, step_in_epoch):
        arrays, rng = self._ark_state()
        return _ark.save_checkpoint(
            cfg.checkpoint_dir, arrays,
            cursor={"epoch_id": int(epoch_id), "step_id": int(step_id),
                    "step_in_epoch": int(step_in_epoch)},
            rng=rng, max_num_checkpoints=cfg.max_num_checkpoints)

    def train(self, num_epochs, event_handler=None, reader=None,
              feed_order=None, checkpoint=None):
        """`checkpoint=ark.CheckpointConfig(...)` turns on durable
        auto-checkpointing: the newest intact serial is restored before
        the first step (params + optimizer slots + RNG cursors; already-
        consumed batches of the resume epoch are skipped, so with a
        deterministic reader the resumed run's fetches are bit-identical
        to the uninterrupted run), and a new serial commits atomically
        every `step_interval` steps / `epoch_interval` epochs with
        retained-N rotation. The legacy `checkpoint_config` constructor
        path is unchanged."""
        event_handler = event_handler or (lambda e: None)
        feeder = DataFeeder(feed_order, program=self.train_program)
        if checkpoint is not None and \
                not isinstance(checkpoint, _ark.CheckpointConfig):
            raise TypeError(
                f"checkpoint= takes an ark.CheckpointConfig, got "
                f"{type(checkpoint).__name__} (the legacy "
                f"trainer.CheckpointConfig goes to Trainer("
                f"checkpoint_config=...))")
        ark_cfg = checkpoint
        # resume the global step counter from the restored checkpoint so the
        # save cadence and trainer_args don't regress after a restart
        step = self.checkpoint_cfg.step_id if self.checkpoint_cfg else 0
        start_epoch = self.checkpoint_cfg.epoch_id if self.checkpoint_cfg else 0
        skip_in_epoch = 0
        if ark_cfg is not None:
            latest = _ark.latest_checkpoint(ark_cfg.checkpoint_dir,
                                            verify=ark_cfg.verify_on_load)
            if latest is not None:
                # checksums already verified picking `latest`
                arrays, manifest = _ark.load_checkpoint(latest, verify=False)
                self._ark_restore(arrays, manifest)
                cursor = manifest.get("cursor", {})
                start_epoch = int(cursor.get("epoch_id", 0))
                step = int(cursor.get("step_id", 0))
                skip_in_epoch = int(cursor.get("step_in_epoch", 0))
        for epoch in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch))
            epoch_ts, epoch_t0 = time.time(), time.perf_counter()
            epoch_start_step = step
            skip = skip_in_epoch if epoch == start_epoch else 0
            for batch_idx, batch in enumerate(reader()):
                if batch_idx < skip:
                    continue   # replayed by the reader, consumed pre-crash
                begin = BeginStepEvent(epoch, step)
                event_handler(begin)
                fetch = [self.loss] + self.metrics if begin.fetch_metrics else []
                out = self._executor_run(feeder.feed(batch), fetch)
                if out and _flags.get_flag("observe"):
                    # fluid-pulse: the loss lands on the health plane's
                    # time-series (non-finite detector food) via the
                    # registry emit path the engine watches
                    _obs_health.note_loss_fetch(out)
                event_handler(EndStepEvent(epoch, step,
                                           [np.asarray(o) for o in out]))
                step += 1
                if ark_cfg is not None and \
                        step % ark_cfg.step_interval == 0:
                    self._ark_save(ark_cfg, epoch, step, batch_idx + 1)
                if self.checkpoint_cfg and \
                        step % self.checkpoint_cfg.step_interval == 0:
                    save_checkpoint(
                        self.exe, self.checkpoint_cfg.checkpoint_dir, 0,
                        self.train_program,
                        trainer_args={"epoch_id": epoch, "step_id": step},
                        max_num_checkpoints=self.checkpoint_cfg.max_num_checkpoints,
                        scope=self.scope)
            if _flags.get_flag("observe"):
                # per-epoch summary (per-step phases come from the
                # executor's steplog; this adds the epoch envelope)
                dur = time.perf_counter() - epoch_t0
                n_steps = step - epoch_start_step
                _obs_metrics.counter(
                    "trainer_epochs_total", "completed epochs").inc()
                _obs_metrics.histogram(
                    "trainer_epoch_seconds", "wall time per epoch"
                ).observe(dur)
                _obs_metrics.gauge(
                    "trainer_last_epoch_steps",
                    "steps run in the most recent epoch").set(n_steps)
                _obs_tracer.get_tracer().record(
                    "epoch", epoch_ts, dur, cat="trainer", epoch=epoch,
                    steps=n_steps,
                    steps_per_sec=round(n_steps / dur, 3) if dur else 0.0)
            if ark_cfg is not None and \
                    (epoch + 1) % ark_cfg.epoch_interval == 0:
                # epoch-boundary serial: cursor points AT the next epoch
                self._ark_save(ark_cfg, epoch + 1, step, 0)
            event_handler(EndEpochEvent(epoch))

    def test(self, reader, feed_order):
        feeder = DataFeeder(feed_order, program=self.test_program)
        totals = None
        count = 0
        for batch in reader():
            out = self.exe.run(self.test_program, feed=feeder.feed(batch),
                               fetch_list=[self.loss] + self.metrics,
                               scope=self.scope)
            vals = [float(np.asarray(o).reshape(-1)[0]) for o in out]
            totals = vals if totals is None else [a + b for a, b in
                                                 zip(totals, vals)]
            count += 1
        return [t / max(count, 1) for t in (totals or [])]

    def save_params(self, param_path):
        fluid_io.save_persistables(self.exe, param_path, self.train_program,
                                   scope=self.scope)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexs):
        targets = [self.loss] if not target_var_indexs else \
            [self.metrics[i] for i in target_var_indexs]
        fluid_io.save_inference_model(param_path, feeded_var_names, targets,
                                      self.exe, self.train_program,
                                      scope=self.scope)

    def stop(self):
        pass


class Inferencer:
    """reference inferencer.py companion."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel=False):
        self.place = place or TPUPlace(0)
        self.scope = Scope()
        self.startup_program = ir.Program()
        self.inference_program = ir.Program()
        with ir.program_guard(self.inference_program, self.startup_program), \
                unique_name.guard():
            self.predict_var = infer_func()
        self.exe = Executor(self.place)
        self.exe.run(self.startup_program, scope=self.scope)
        fluid_io.load_persistables(self.exe, param_path,
                                   self.inference_program, scope=self.scope)
        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs):
        return self.exe.run(self.inference_program, feed=inputs,
                            fetch_list=[self.predict_var], scope=self.scope)
