"""Structured runtime checks.

Capability parity with the reference's enforce layer (reference:
paddle/fluid/platform/enforce.h — PADDLE_ENFORCE* :232-272 and the
`EnforceNotMet` exception :66 that carries a captured stack). Graph-build
and host-side runtime code raise `EnforceNotMet` with the failing
condition, a formatted message and the offending frame, so user errors
surface at the API boundary instead of deep inside a JAX trace.
"""

from __future__ import annotations

import traceback


class EnforceNotMet(RuntimeError):
    """reference EnforceNotMet (enforce.h:66): message + capture site."""

    def __init__(self, message: str):
        # innermost frame OUTSIDE this module = the enforcement site
        frame = None
        for f in reversed(traceback.extract_stack()):
            if f.filename != __file__:
                frame = f
                break
        where = (f"\n  [enforced at {frame.filename}:{frame.lineno} "
                 f"in {frame.name}]") if frame else ""
        super().__init__(message + where)


def enforce(cond, msg="enforce failed", *fmt_args):
    if not cond:
        raise EnforceNotMet(msg % fmt_args if fmt_args else msg)


def enforce_eq(a, b, msg=None):
    if a != b:
        raise EnforceNotMet(msg or f"enforce_eq failed: {a!r} != {b!r}")


def enforce_ne(a, b, msg=None):
    if a == b:
        raise EnforceNotMet(msg or f"enforce_ne failed: both {a!r}")


def enforce_gt(a, b, msg=None):
    if not a > b:
        raise EnforceNotMet(msg or f"enforce_gt failed: {a!r} <= {b!r}")


def enforce_ge(a, b, msg=None):
    if not a >= b:
        raise EnforceNotMet(msg or f"enforce_ge failed: {a!r} < {b!r}")


def enforce_lt(a, b, msg=None):
    if not a < b:
        raise EnforceNotMet(msg or f"enforce_lt failed: {a!r} >= {b!r}")


def enforce_le(a, b, msg=None):
    if not a <= b:
        raise EnforceNotMet(msg or f"enforce_le failed: {a!r} > {b!r}")


def enforce_not_none(v, msg=None):
    if v is None:
        raise EnforceNotMet(msg or "enforce_not_none failed")
    return v


def enforce_shape_match(shape, expected, msg=None):
    """Dims match where expected is not -1 (dynamic)."""
    shape, expected = tuple(shape), tuple(expected)
    ok = len(shape) == len(expected) and all(
        e == -1 or s == e or s == -1 for s, e in zip(shape, expected))
    if not ok:
        raise EnforceNotMet(msg or f"shape mismatch: got {shape}, "
                                   f"expected {expected}")
