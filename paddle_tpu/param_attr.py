"""ParamAttr (reference: python/paddle/fluid/param_attr.py)."""

from __future__ import annotations

from . import initializer as init


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 sharding=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        # TPU extension: optional PartitionSpec-like tuple for GSPMD sharding.
        self.sharding = sharding

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, init.Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    def __init__(self, dim=None, **kw):
        super().__init__(**kw)
        self.dim = dim
