"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler.cc).

TPU-native redesign: the reference's CUPTI device tracer + event profiler map
onto the JAX/XLA profiler, which captures both host events and device (TPU)
trace timelines into TensorBoard/perfetto format. The `profiler` context
manager keeps the reference API shape (state, sorted_key, output path).

The host-event table behind `record_event` / `print_host_events` /
`export_chrome_tracing` is the `observe.tracer` ring buffer (fluid-scope,
round 8): events are BOUNDED (old ones fall off the back instead of
growing host memory across a long run), nested spans carry depth/parent,
and executor step phases, trainer epoch marks and RPC spans share the
same timeline + export path.
"""

from __future__ import annotations

import contextlib
import os
import time
import warnings

import jax

from .observe import tracer as _tracer_mod

# TPU-native states. "GPU" is accepted as a deprecated alias (reference
# scripts pass it); there is no CUDA device here — the XLA trace simply
# captures whatever accelerator backend is active.
_STATES = ("CPU", "TPU", "All")
_DEPRECATED_STATES = ("GPU",)


def _check_state(state: str) -> str:
    if state in _DEPRECATED_STATES:
        warnings.warn(
            f"profiler state {state!r} is a deprecated alias on the "
            f"TPU-native build; use 'TPU' (or 'All')", DeprecationWarning,
            stacklevel=3)
        return state
    if state not in _STATES:
        raise ValueError(
            f"state must be CPU / TPU / All (got {state!r}; 'GPU' is "
            f"accepted as a deprecated alias)")
    return state


def _host_tracer() -> _tracer_mod.Tracer:
    return _tracer_mod.get_tracer()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """reference profiler.py:profiler — wraps jax.profiler trace capture."""
    _check_state(state)
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        dt = time.time() - t0
        print(f"[paddle_tpu.profiler] trace written to {profile_path} "
              f"(wall {dt:.3f}s); view with TensorBoard or perfetto")


@contextlib.contextmanager
def record_event(name: str):
    """reference platform::RecordEvent analog -> jax named annotation.
    Events also land in the bounded host-event ring (print_host_events)
    and the chrome trace export (export_chrome_tracing). Recorded even
    when the body raises — the failing iteration is usually the one being
    profiled."""
    with jax.profiler.TraceAnnotation(name):
        with _host_tracer().span(name, cat="host"):
            yield


def start_profiler(state="All", profile_path="/tmp/profile"):
    _check_state(state)
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()


def reset_profiler():
    """Clear the host-event ring (reference ResetProfiler)."""
    _host_tracer().clear()


@contextlib.contextmanager
def cuda_profiler(*a, **kw):
    """Accepted for reference API parity; TPU traces are captured by
    `profiler` above."""
    yield


def print_host_events(sorted_key="total"):
    """Aggregated host-event table (reference DisableProfiler's printed
    table, profiler.cc:448). Device-level op times live in the XLA trace
    captured by `profiler` (TensorBoard/perfetto) — under jit there are no
    per-op kernel launches to time on the host, by design."""
    agg = _host_tracer().aggregate(cat="host")
    keyfn = {"total": lambda kv: -kv[1][1], "calls": lambda kv: -kv[1][0],
             "max": lambda kv: -kv[1][2], "min": lambda kv: kv[1][3],
             "ave": lambda kv: -kv[1][1] / kv[1][0]}.get(
        sorted_key, lambda kv: -kv[1][1])
    rows = sorted(agg.items(), key=keyfn)
    print(f"{'Event':<40} {'Calls':>8} {'Total(s)':>12} {'Avg(ms)':>10} "
          f"{'Max(ms)':>10} {'Min(ms)':>10}")
    for name, (calls, total, mx, mn) in rows:
        print(f"{name:<40} {calls:>8} {total:>12.4f} "
              f"{1000 * total / calls:>10.3f} {1000 * mx:>10.3f} "
              f"{1000 * mn:>10.3f}")
    return rows


def export_chrome_tracing(path: str):
    """Write recorded host events as chrome://tracing JSON (reference
    tools/timeline.py:21 converts the profiler proto the same way; device
    timelines come from the perfetto trace jax.profiler writes). Exports
    the WHOLE telemetry timeline — record_event spans plus executor step
    phases and any other tracer category."""
    return _host_tracer().export_chrome(path)
