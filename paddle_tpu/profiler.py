"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler.cc).

TPU-native redesign: the reference's CUPTI device tracer + event profiler map
onto the JAX/XLA profiler, which captures both host events and device (TPU)
trace timelines into TensorBoard/perfetto format. The `profiler` context
manager keeps the reference API shape (state, sorted_key, output path).
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

import jax

_events = []


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """reference profiler.py:profiler — wraps jax.profiler trace capture."""
    if state not in ("CPU", "GPU", "TPU", "All"):
        raise ValueError("state must be CPU / TPU / All")
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        dt = time.time() - t0
        print(f"[paddle_tpu.profiler] trace written to {profile_path} "
              f"(wall {dt:.3f}s); view with TensorBoard or perfetto")


@contextlib.contextmanager
def record_event(name: str):
    """reference platform::RecordEvent analog -> jax named annotation."""
    with jax.profiler.TraceAnnotation(name):
        t0 = time.time()
        yield
        _events.append((name, time.time() - t0))


def start_profiler(state="All", profile_path="/tmp/profile"):
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()


def reset_profiler():
    _events.clear()


@contextlib.contextmanager
def cuda_profiler(*a, **kw):
    """Accepted for reference API parity; TPU traces are captured by
    `profiler` above."""
    yield


def print_host_events():
    agg = defaultdict(lambda: [0, 0.0])
    for name, dt in _events:
        agg[name][0] += 1
        agg[name][1] += dt
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
    print(f"{'Event':<40} {'Calls':>8} {'Total(s)':>12} {'Avg(ms)':>10}")
    for name, (calls, total) in rows:
        print(f"{name:<40} {calls:>8} {total:>12.4f} {1000*total/calls:>10.3f}")
