"""Profiler (reference: python/paddle/fluid/profiler.py + platform/profiler.cc).

TPU-native redesign: the reference's CUPTI device tracer + event profiler map
onto the JAX/XLA profiler, which captures both host events and device (TPU)
trace timelines into TensorBoard/perfetto format. The `profiler` context
manager keeps the reference API shape (state, sorted_key, output path).
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

import jax

_events = []


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """reference profiler.py:profiler — wraps jax.profiler trace capture."""
    if state not in ("CPU", "GPU", "TPU", "All"):
        raise ValueError("state must be CPU / TPU / All")
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)
    t0 = time.time()
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        dt = time.time() - t0
        print(f"[paddle_tpu.profiler] trace written to {profile_path} "
              f"(wall {dt:.3f}s); view with TensorBoard or perfetto")


@contextlib.contextmanager
def record_event(name: str):
    """reference platform::RecordEvent analog -> jax named annotation.
    Events also land in the host table (print_host_events) and the chrome
    trace export (export_chrome_tracing)."""
    with jax.profiler.TraceAnnotation(name):
        t0 = time.time()
        try:
            yield
        finally:
            # record even when the body raises — the failing iteration is
            # usually the one being profiled
            _events.append((name, t0, time.time() - t0))


def start_profiler(state="All", profile_path="/tmp/profile"):
    os.makedirs(profile_path, exist_ok=True)
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()


def reset_profiler():
    _events.clear()


@contextlib.contextmanager
def cuda_profiler(*a, **kw):
    """Accepted for reference API parity; TPU traces are captured by
    `profiler` above."""
    yield


def print_host_events(sorted_key="total"):
    """Aggregated host-event table (reference DisableProfiler's printed
    table, profiler.cc:448). Device-level op times live in the XLA trace
    captured by `profiler` (TensorBoard/perfetto) — under jit there are no
    per-op kernel launches to time on the host, by design."""
    agg = defaultdict(lambda: [0, 0.0, 0.0, float("inf")])
    for name, _t0, dt in _events:
        a = agg[name]
        a[0] += 1
        a[1] += dt
        a[2] = max(a[2], dt)
        a[3] = min(a[3], dt)
    keyfn = {"total": lambda kv: -kv[1][1], "calls": lambda kv: -kv[1][0],
             "max": lambda kv: -kv[1][2], "min": lambda kv: kv[1][3],
             "ave": lambda kv: -kv[1][1] / kv[1][0]}.get(
        sorted_key, lambda kv: -kv[1][1])
    rows = sorted(agg.items(), key=keyfn)
    print(f"{'Event':<40} {'Calls':>8} {'Total(s)':>12} {'Avg(ms)':>10} "
          f"{'Max(ms)':>10} {'Min(ms)':>10}")
    for name, (calls, total, mx, mn) in rows:
        print(f"{name:<40} {calls:>8} {total:>12.4f} "
              f"{1000 * total / calls:>10.3f} {1000 * mx:>10.3f} "
              f"{1000 * mn:>10.3f}")
    return rows


def export_chrome_tracing(path: str):
    """Write recorded host events as chrome://tracing JSON (reference
    tools/timeline.py:21 converts the profiler proto the same way; device
    timelines come from the perfetto trace jax.profiler writes)."""
    import json
    events = [{"name": name, "ph": "X", "pid": 0, "tid": 0,
               "ts": int(t0 * 1e6), "dur": int(dt * 1e6),
               "cat": "host"} for name, t0, dt in _events]
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path
