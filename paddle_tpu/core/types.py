"""Core type vocabulary for the program IR.

Capability parity with the reference's ``VarType`` proto enum
(reference: paddle/fluid/framework/framework.proto:97-160) and its dtype table.
TPU-native redesign: dtypes are plain strings mapping 1:1 onto jnp dtypes; the
variable kinds collapse to what a functional XLA runtime actually needs.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class VarKind(enum.Enum):
    # Dense tensor, optionally carrying sequence lengths (the LoDTensor analog:
    # reference lod_tensor.h:110 — we use padded dense + per-row lengths).
    DENSE_TENSOR = "dense_tensor"
    # Sparse row-slice tensor (reference selected_rows.h:30): (rows, values).
    SELECTED_ROWS = "selected_rows"
    # Array of tensors (reference lod_tensor_array.h) for control-flow plumbing.
    TENSOR_ARRAY = "tensor_array"
    # Data-source handle (reference reader.h:28).
    READER = "reader"
    # Scope(s) kept by control-flow ops (reference recurrent_op.cc StepScopes).
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


# Canonical dtype strings -> numpy/jnp dtypes.
_DTYPES = {
    "bool": np.bool_,
    "int8": np.int8,
    "uint8": np.uint8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "float16": np.float16,
    "bfloat16": jnp.bfloat16,
    "float32": np.float32,
    "float64": np.float64,
}

_ALIASES = {
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
    "bf16": "bfloat16",
    "half": "float16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")


def canonical_dtype(dtype) -> str:
    """Normalize a user-supplied dtype (str / np.dtype / jnp type) to a canonical string."""
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
    else:
        name = jnp.dtype(dtype).name
        name = _ALIASES.get(name, name)
    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype: {dtype!r}")
    return name


def np_dtype(dtype) -> np.dtype:
    return jnp.dtype(_DTYPES[canonical_dtype(dtype)])


def index_dtype():
    """Runtime dtype backing the reference's int64 index contract.

    Under JAX's default x32 mode int64 arrays do not exist: an
    ``astype(int64)`` silently produces int32 (plus a user warning). Ops
    that declare int64 outputs for reference parity therefore cast through
    this helper — int32 in x32 mode (documented downcast), widening to
    real int64 only when ``jax_enable_x64`` is set.
    """
    import jax

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def is_float_dtype(dtype) -> bool:
    return canonical_dtype(dtype) in FLOAT_DTYPES
