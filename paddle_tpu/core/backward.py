"""Program-level autodiff: `append_backward`.

Capability parity with the reference's program-level backward pass
(reference: python/paddle/fluid/backward.py:450 `append_backward`,
`_append_backward_ops_` :295, `_addup_repetitive_outputs_` :120,
`_remove_no_grad_branch_` :189).

TPU-native redesign: instead of ~200 hand-written GradOpDescMakers
(reference: grad_op_desc_maker.h:34), every forward op gets ONE generic grad
op whose lowering re-traces the forward rule under `jax.vjp`
(core/lowering.py). The graph-level concerns stay explicit in the IR exactly
as in the reference: fan-in gradient accumulation inserts `sum` ops, and
stop_gradient / no_grad_set prune dead branches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import ir, registry
from .ir import GRAD_SUFFIX, grad_var_name
from .registry import EMPTY_VAR, FWD_OP_ATTR, GRAD_OP_SUFFIX

# Ops that never need/propagate gradients.
_NON_DIFF_OPS = {"fill_constant", "uniform_random", "gaussian_random", "feed",
                 "fetch", "accuracy", "increment", "assign_value", "shape",
                 "iota", "truncated_gaussian_random"}


def _grad_contrib_name(name: str, k: int) -> str:
    return f"{name}{GRAD_SUFFIX}@RENAME@{k}"


def append_backward(loss: ir.Variable,
                    parameter_list: Optional[Sequence[str]] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    ) -> List[Tuple[ir.Variable, ir.Variable]]:
    """Append gradient ops for `loss` to its program's global block.

    Returns [(parameter, gradient_variable)] pairs, like the reference.
    """
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    # 1. d(loss)/d(loss) = 1.
    loss_grad = _ensure_grad_var(block, loss)
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad.name]},
        attrs={"shape": list(loss.shape) if loss.shape else [1],
               "dtype": loss.dtype, "value": 1.0},
    )

    # 2. Reverse walk emitting grad ops; collect per-var grad contributions.
    loss_idx = _find_producer_idx(block, loss.name)
    contribs: Dict[str, List[str]] = {loss.name: [loss_grad.name]}
    fwd_ops = list(enumerate(block.ops[: loss_idx + 1]))
    grad_ops_meta = []  # (grad_op, [contributed var names])

    for idx, op in reversed(fwd_ops):
        if op.type in _NON_DIFF_OPS or op.type.endswith(GRAD_OP_SUFFIX):
            continue
        out_has_grad = any(n in contribs for ns in op.outputs.values() for n in ns)
        if not out_has_grad:
            continue
        if op.type == "while":
            raise NotImplementedError(
                "gradients cannot flow through a `while` loop on TPU "
                "(lax.while_loop is not reverse-differentiable); express the "
                "recurrence with layers.StaticRNN / dynamic_lstm / dynamic_gru "
                "(lax.scan-based), or mark the loop outputs stop_gradient")
        grad_targets = _grad_needing_inputs(block, op, no_grad, parameter_list)
        if not grad_targets:
            continue

        # out-grad inputs: canonical @GRAD names (finalized later by sum ops).
        out_grad_names = []
        for ns in op.outputs.values():
            for n in ns:
                if n in contribs:
                    out_grad_names.append(grad_var_name(n))

        # in-grad outputs: fresh contribution names per target var.
        out_names, touched = [], []
        for n in grad_targets:
            k = len(contribs.setdefault(n, []))
            cname = grad_var_name(n) if k == 0 else _grad_contrib_name(n, k)
            contribs[n].append(cname)
            out_names.append(cname)
            touched.append(n)
            _ensure_grad_var(block, block.var(n), cname)

        fwd_desc = op.to_dict()
        fwd_desc["__idx__"] = idx
        grad_op = ir.Operator(
            block, op.type + GRAD_OP_SUFFIX,
            inputs={"FwdIn": sorted({n for ns in op.inputs.values() for n in ns}),
                    "OutGrad": out_grad_names},
            outputs={"InGrad": out_names},
            attrs={FWD_OP_ATTR: fwd_desc},
        )
        block.ops.append(grad_op)
        program._bump()
        grad_ops_meta.append((grad_op, touched))

    # 3. Fan-in accumulation: for vars with >1 contributions, rename the first
    # contribution and insert a `sum` op after the last contribution
    # (reference `_addup_repetitive_outputs_`).
    _insert_sum_ops(block, contribs, loss.name)

    # 4. Collect (param, grad) pairs.
    params = block.all_parameters()
    if parameter_list is not None:
        wanted = set(parameter_list)
        params = [p for p in params if p.name in wanted]
    pairs = []
    for p in params:
        if not p.trainable or p.name in no_grad:
            continue
        gname = grad_var_name(p.name)
        if p.name in contribs:
            pairs.append((p, block.var(gname)))
    return pairs


def _insert_sum_ops(block: ir.Block, contribs: Dict[str, List[str]], loss_name: str):
    multi = {n: cs for n, cs in contribs.items() if len(cs) > 1 and n != loss_name}
    if not multi:
        return
    # Rename the k=0 contribution (which took the canonical name) in its
    # producing op, then sum all contributions into the canonical name.
    for n, cs in multi.items():
        canonical = grad_var_name(n)
        renamed0 = _grad_contrib_name(n, 0)
        last_idx = -1
        first = True
        for i, op in enumerate(block.ops):
            for slot, names in op.outputs.items():
                for j, out in enumerate(names):
                    if out == canonical and op.type.endswith(GRAD_OP_SUFFIX) and first:
                        names[j] = renamed0
                        first = False
                        last_idx = max(last_idx, i)
                    elif out in cs:
                        last_idx = max(last_idx, i)
        srcs = [renamed0] + cs[1:]
        _ensure_grad_var(block, block.var(n), renamed0)
        block.insert_op(last_idx + 1, "sum",
                        inputs={"X": srcs}, outputs={"Out": [canonical]})


def _grad_needing_inputs(block, op, no_grad, parameter_list) -> List[str]:
    """Inputs of `op` that should receive gradients (dedup, order-stable)."""
    seen, out = set(), []
    for ns in op.inputs.values():
        for n in ns:
            if n in seen or n == EMPTY_VAR:
                continue
            seen.add(n)
            if n in no_grad:
                continue
            if not block.has_var(n):
                continue
            v = block.var(n)
            from .types import is_float_dtype
            if v.stop_gradient or not is_float_dtype(v.dtype):
                continue
            out.append(n)
    return out


def _ensure_grad_var(block: ir.Block, fwd_var: ir.Variable, name: Optional[str] = None):
    name = name or grad_var_name(fwd_var.name)
    if name in block.vars:
        return block.vars[name]
    return block.create_var(name=name, shape=fwd_var.shape, dtype=fwd_var.dtype,
                            stop_gradient=True)


def _find_producer_idx(block: ir.Block, name: str) -> int:
    for i in range(len(block.ops) - 1, -1, -1):
        if name in block.ops[i].output_arg_names:
            return i
    raise ValueError(f"loss var {name!r} has no producing op in block")


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference calc_gradient analog (backward.py:667): gradients of
    `targets` w.r.t. `inputs`."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("calc_gradient currently supports one target")
    pairs = append_backward(targets[0], no_grad_set=no_grad_set,
                            parameter_list=None)
    block = targets[0].block
    outs = []
    for v in inputs:
        gname = grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs
