"""Program-level autodiff: `append_backward`.

Capability parity with the reference's program-level backward pass
(reference: python/paddle/fluid/backward.py:450 `append_backward`,
`_append_backward_ops_` :295, `_addup_repetitive_outputs_` :120,
`_remove_no_grad_branch_` :189).

TPU-native redesign: instead of ~200 hand-written GradOpDescMakers
(reference: grad_op_desc_maker.h:34), every forward op gets ONE generic grad
op whose lowering re-traces the forward rule under `jax.vjp`
(core/lowering.py). The graph-level concerns stay explicit in the IR exactly
as in the reference: fan-in gradient accumulation inserts `sum` ops, and
stop_gradient / no_grad_set prune dead branches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import ir, registry
from .ir import GRAD_SUFFIX, grad_var_name
from .registry import EMPTY_VAR, FWD_OP_ATTR, GRAD_OP_SUFFIX

# Ops that never need/propagate gradients.
_NON_DIFF_OPS = {"fill_constant", "uniform_random", "gaussian_random", "feed",
                 "fetch", "accuracy", "increment", "assign_value", "shape",
                 "iota", "truncated_gaussian_random"}


def _grad_contrib_name(name: str, k: int) -> str:
    return f"{name}{GRAD_SUFFIX}@RENAME@{k}"


def append_backward(loss: ir.Variable,
                    parameter_list: Optional[Sequence[str]] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    ) -> List[Tuple[ir.Variable, ir.Variable]]:
    """Append gradient ops for `loss` to its program's global block.

    Returns [(parameter, gradient_variable)] pairs, like the reference.
    """
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    # 1. d(loss)/d(loss) = 1.
    loss_grad = _ensure_grad_var(block, loss)
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad.name]},
        attrs={"shape": list(loss.shape) if loss.shape else [1],
               "dtype": loss.dtype, "value": 1.0,
               "__role__": "backward"},
    )

    # 2. Reverse walk emitting grad ops; collect per-var grad contributions.
    #
    # Contributions are tracked in EPOCHS: programs are not strictly SSA (a
    # `while` loop or `assign(x, out=y)` re-writes an existing name), and
    # grad contributions to different SSA "versions" of a name must never be
    # summed together. When the reverse walk passes an op that WRITES var n,
    # n's current epoch closes and a fresh one opens; each epoch's
    # contributions are summed separately into the canonical `n@GRAD` name,
    # and ordered execution (grad ops run reverse-fwd) makes the canonical
    # name hold the right epoch's value at every read point. (The reference
    # gets the same effect with per-step scopes, while_op.cc:96.)
    loss_idx = _find_producer_idx(block, loss.name)
    # var -> list of epochs; each epoch is a list of (contrib_name, grad_op)
    contribs: Dict[str, List[List[Tuple[str, Optional[ir.Operator]]]]] = {
        loss.name: [[(loss_grad.name, block.ops[-1])]]}
    rename_counter: Dict[str, int] = {}
    fwd_ops = list(enumerate(block.ops[: loss_idx + 1]))

    def _has_grad(n):
        # only the CURRENT epoch's contributions are reachable by ops at this
        # point of the reverse walk; earlier epochs belong to later SSA
        # versions of the name (severed by a write barrier)
        return n in contribs and bool(contribs[n][-1])

    def _write_barrier(op):
        # this op produced these names; earlier consumers see the previous
        # SSA version, so their grads start a new epoch. Applies to EVERY
        # producing op — a non-diff op (fill_constant out=x) severs the
        # dependency just as thoroughly as a diff one.
        for ns in op.outputs.values():
            for n in ns:
                if n in contribs:
                    contribs[n].append([])

    for idx, op in reversed(fwd_ops):
        if op.type.endswith(GRAD_OP_SUFFIX):
            continue
        if op.type in _NON_DIFF_OPS:
            _write_barrier(op)
            continue
        out_has_grad = any(_has_grad(n) for ns in op.outputs.values() for n in ns)
        if not out_has_grad:
            _write_barrier(op)
            continue
        if op.type == "while":
            raise NotImplementedError(
                "gradients cannot flow through an unbounded `while` loop on "
                "TPU (lax.while_loop is not reverse-differentiable); pass "
                "While(cond, max_iters=N) for a scan-based differentiable "
                "loop, or use layers.StaticRNN / DynamicRNN / dynamic_lstm")
        grad_targets = _grad_needing_inputs(block, op, no_grad, parameter_list)

        # out-grad inputs: canonical @GRAD names.
        out_grad_names = []
        for ns in op.outputs.values():
            for n in ns:
                if _has_grad(n):
                    out_grad_names.append(grad_var_name(n))

        _write_barrier(op)

        if not grad_targets:
            continue

        # in-grad outputs: contribution names within the target's epoch.
        out_names, touched = [], []
        for n in grad_targets:
            epochs = contribs.setdefault(n, [[]])
            epoch = epochs[-1]
            if not epoch:
                cname = grad_var_name(n)
            else:
                k = rename_counter.get(n, 0) + 1
                rename_counter[n] = k
                cname = _grad_contrib_name(n, k)
            out_names.append(cname)
            touched.append(n)
            _ensure_grad_var(block, block.var(n), cname)

        fwd_desc = op.to_dict()
        fwd_desc["__idx__"] = idx
        grad_op = ir.Operator(
            block, op.type + GRAD_OP_SUFFIX,
            inputs={"FwdIn": sorted({n for ns in op.inputs.values() for n in ns}),
                    "OutGrad": out_grad_names},
            outputs={"InGrad": out_names},
            attrs={FWD_OP_ATTR: fwd_desc, "__role__": "backward"},
        )
        block.ops.append(grad_op)
        program._bump()
        for n, cname in zip(touched, out_names):
            contribs[n][-1].append((cname, grad_op))

    # 3. Fan-in accumulation per epoch: rename the epoch's first contribution
    # (which took the canonical name) and insert a `sum` op right after the
    # epoch's last contribution (reference `_addup_repetitive_outputs_`).
    _insert_sum_ops(block, contribs, loss.name, rename_counter)

    # 4. Collect (param, grad) pairs.
    params = block.all_parameters()
    if parameter_list is not None:
        wanted = set(parameter_list)
        params = [p for p in params if p.name in wanted]
    pairs = []
    for p in params:
        if not p.trainable or p.name in no_grad:
            continue
        gname = grad_var_name(p.name)
        if p.name in contribs:
            pairs.append((p, block.var(gname)))
    return pairs


def _insert_sum_ops(block: ir.Block, contribs, loss_name: str,
                    rename_counter: Dict[str, int]):
    # Collect (var, epoch) groups needing a sum, with their op references.
    pending = []  # (n, [(cname, op), ...])
    for n, epochs in contribs.items():
        if n == loss_name:
            continue
        for epoch in epochs:
            if len(epoch) > 1:
                pending.append((n, epoch))
    if not pending:
        return
    for n, epoch in pending:
        canonical = grad_var_name(n)
        # rename the epoch's first contribution (it took the canonical name)
        first_name, first_op = epoch[0]
        k = rename_counter.get(n, 0) + 1
        rename_counter[n] = k
        renamed0 = _grad_contrib_name(n, k)
        for slot, names in first_op.outputs.items():
            for j, out in enumerate(names):
                if out == first_name:
                    names[j] = renamed0
        _ensure_grad_var(block, block.var(n), renamed0)
        srcs = [renamed0] + [c for c, _ in epoch[1:]]
        # insert the sum right after the epoch's last contributing op
        ops_in_epoch = {id(op) for _, op in epoch}
        last_idx = max(i for i, op in enumerate(block.ops)
                       if id(op) in ops_in_epoch)
        block.insert_op(last_idx + 1, "sum",
                        inputs={"X": srcs}, outputs={"Out": [canonical]},
                        attrs={"__role__": "backward"})


def _grad_needing_inputs(block, op, no_grad, parameter_list) -> List[str]:
    """Inputs of `op` that should receive gradients (dedup, order-stable)."""
    seen, out = set(), []
    for ns in op.inputs.values():
        for n in ns:
            if n in seen or n == EMPTY_VAR:
                continue
            seen.add(n)
            if n in no_grad:
                continue
            if not block.has_var(n):
                continue
            v = block.var(n)
            from .types import is_float_dtype
            if v.stop_gradient or not is_float_dtype(v.dtype):
                continue
            out.append(n)
    return out


def _ensure_grad_var(block: ir.Block, fwd_var: ir.Variable, name: Optional[str] = None):
    name = name or grad_var_name(fwd_var.name)
    if name in block.vars:
        return block.vars[name]
    return block.create_var(name=name, shape=fwd_var.shape, dtype=fwd_var.dtype,
                            stop_gradient=True)


def _find_producer_idx(block: ir.Block, name: str) -> int:
    for i in range(len(block.ops) - 1, -1, -1):
        if name in block.ops[i].output_arg_names:
            return i
    raise ValueError(f"loss var {name!r} has no producing op in block")


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference calc_gradient analog (backward.py:667): gradients of
    `targets` w.r.t. `inputs`."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("calc_gradient currently supports one target")
    pairs = append_backward(targets[0], no_grad_set=no_grad_set,
                            parameter_list=None)
    block = targets[0].block
    outs = []
    for v in inputs:
        gname = grad_var_name(v.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs
