"""Program IR: Variable / Operator / Block / Program.

Capability parity with the reference's ProgramDesc stack:
  - proto schema            reference: paddle/fluid/framework/framework.proto:35-169
  - C++ desc wrappers       reference: paddle/fluid/framework/{program,block,op,var}_desc.*
  - Python graph builders   reference: python/paddle/fluid/framework.py:130-1959

TPU-native redesign: there is no C++/Python desc split and no per-op kernel
objects. The IR is a plain Python dataclass tree, serializable to JSON, and the
*meaning* of an op is its registered JAX lowering rule (see registry.py). An
entire Block lowers to one XLA computation (executor.py), so the IR only needs
to describe dataflow, not execution.
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

from . import types
from .types import VarKind

# Name suffix conventions shared with the reference's autodiff
# (reference: python/paddle/fluid/backward.py — `var@GRAD` naming).
GRAD_SUFFIX = "@GRAD"
# Companion variable carrying per-row sequence lengths for variable-length
# (LoD-analog) tensors: padded dense data + `name@SEQLEN` int32[batch].
SEQLEN_SUFFIX = "@SEQLEN"
# fluid-decode: persistable-but-ephemeral device STATE (the paged KV
# cache). Rides the scope like an optimizer accumulator but is never
# serialized: io save/load predicates skip the suffix, and the serving
# registry re-materializes zeros of the manifest-declared shape at load.
KV_CACHE_SUFFIX = "@KV_CACHE"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


def seqlen_var_name(name: str, level: int = 0) -> str:
    """Companion name for the lengths of LoD level `level` (0 = outermost).
    Level 0 keeps the historical bare suffix; deeper levels append the
    level index (nested LoD: data [B, S, T, ...] has `@SEQLEN` = [B] outer
    counts and `@SEQLEN.1` = [B, S] inner lengths)."""
    return name + SEQLEN_SUFFIX + (f".{level}" if level else "")


class Variable:
    """A named value in a Block (reference framework.py:130 `Variable`).

    ``shape`` may contain -1 for dimensions unknown until runtime (batch).
    ``lod_level > 0`` marks a variable-length sequence tensor: at runtime it is
    a padded dense array plus a `@SEQLEN` companion with true row lengths.
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Sequence[int] = (),
        dtype: str = "float32",
        kind: VarKind = VarKind.DENSE_TENSOR,
        lod_level: int = 0,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = types.canonical_dtype(dtype)
        self.kind = kind
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data

    # ---- operator sugar (reference: layers/math_op_patch.py) is attached in
    # layers/math_op_patch.py to avoid a core->layers dependency.

    @property
    def grad_name(self) -> str:
        return grad_var_name(self.name)

    def astype(self, dtype):
        from ..layers import tensor as _t  # local import: layer sugar

        return _t.cast(self, dtype)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "kind": self.kind.value,
            "lod_level": self.lod_level,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
            "optimize_attr": getattr(self, "optimize_attr", None),
            "sharding": list(s) if (s := getattr(self, "sharding", None)) else None,
        }

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, lod={self.lod_level})")


class Parameter(Variable):
    """A trainable persistable variable (reference framework.py:1759)."""

    def __init__(self, block, name, shape, dtype, trainable=True,
                 regularizer=None, gradient_clip=None, is_distributed=False,
                 sharding=None, **kw):
        kw.setdefault("persistable", True)
        super().__init__(block, name, shape, dtype, **kw)
        self.trainable = trainable
        self.regularizer = regularizer
        self.gradient_clip = gradient_clip
        self.is_distributed = is_distributed
        # Optional PartitionSpec-like tuple consumed by parallel/transpiler.py.
        self.sharding = sharding


# Package root for trimming creation tracebacks: frames inside the
# framework are plumbing, the first frames OUTSIDE it are where the user
# actually built the op (the reference stored the same thing as the
# `op_callstack` attr on every OpDesc).
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep


def _creation_site(max_frames: int = 2) -> Optional[List[str]]:
    """Innermost non-framework frames of the current stack, formatted
    `file:line in func`. Walks raw frame objects (no source loading), so
    the per-op build cost is a few µs."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover - shallow stack
        return None
    site: List[str] = []
    depth = 0
    while f is not None and len(site) < max_frames and depth < 32:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            site.append(f"{fn}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
        depth += 1
    return site or None


class Operator:
    """One op invocation (reference framework.py:418 / op_desc.h:29).

    inputs/outputs map slot name -> list of variable names. attrs must be
    JSON-serializable (sub-blocks are referenced by block index, as in the
    reference's BlockDesc attr).
    """

    def __init__(self, block: "Block", type: str,
                 inputs: Optional[Dict[str, Any]] = None,
                 outputs: Optional[Dict[str, Any]] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 capture_site: bool = True):
        self.block = block
        self.type = type
        self.inputs = {k: _as_name_list(v) for k, v in (inputs or {}).items() if v is not None}
        self.outputs = {k: _as_name_list(v) for k, v in (outputs or {}).items() if v is not None}
        self.attrs = dict(attrs or {})
        # trimmed creation traceback for diagnostics (analysis/): not
        # serialized — a JSON round-trip yields ops with no site, and the
        # verifier falls back to (block, op index) provenance
        self._creation_site = _creation_site() if capture_site else None

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": copy.deepcopy(self.attrs),
        }

    def __repr__(self):
        return f"Operator({self.type}: {self.inputs} -> {self.outputs})"


def _as_name_list(v) -> List[str]:
    if isinstance(v, (list, tuple)):
        return [x.name if isinstance(x, Variable) else str(x) for x in v]
    return [v.name if isinstance(v, Variable) else str(v)]


class Block:
    """An ordered op list + var table, possibly nested (reference block_desc.h:38)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    # -- var management -------------------------------------------------
    def create_var(self, name=None, **kw) -> Variable:
        if name is None:
            from .. import unique_name
            name = unique_name.generate("tmp")
        v = Variable(self, name=name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name, shape, dtype, **kw) -> Parameter:
        p = Parameter(self, name, shape, dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = self.program.blocks[blk.parent_idx] if blk.parent_idx >= 0 else None
        return None

    @property
    def parent(self) -> Optional["Block"]:
        return self.program.blocks[self.parent_idx] if self.parent_idx >= 0 else None

    # -- op management --------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump()
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        return op

    def insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump()
        return op

    def remove_op(self, index: int):
        del self.ops[index]
        self.program._bump()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }

    def __repr__(self):
        return f"Block(idx={self.idx}, ops={len(self.ops)}, vars={len(self.vars)})"


class Program:
    """A list of nested blocks; block 0 is global (reference framework.py:1249).

    `_version` increments on any mutation so executors can cache compiled
    lowerings per (program, version).
    """

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self._version = 0
        # process-unique id for executor cache keys: id() can be recycled
        # after GC and serve a stale compiled step
        self._uid = next(Program._uid_counter)
        self._seed: Optional[int] = None  # random_seed analog
        self._is_inference = False

    def _bump(self):
        self._version += 1

    # -- block management ------------------------------------------------
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(blk)
        self._current_block_idx = blk.idx
        self._bump()
        return blk

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = seed
        self._bump()

    # -- cloning / pruning (reference framework.py Program.clone/_prune) --
    def clone(self, for_test: bool = False) -> "Program":
        p = Program.from_dict(self.to_dict())
        p._seed = self._seed
        # Re-attach non-serializable Parameter metadata (regularizer /
        # gradient_clip are python objects; JSON round-trip drops them).
        for src_blk, dst_blk in zip(self.blocks, p.blocks):
            for name, src in src_blk.vars.items():
                dst = dst_blk.vars.get(name)
                if isinstance(src, Parameter) and isinstance(dst, Parameter):
                    dst.regularizer = src.regularizer
                    dst.gradient_clip = src.gradient_clip
                    dst.sharding = src.sharding
                    dst.trainable = src.trainable
                    dst.is_distributed = src.is_distributed
                    if hasattr(src, "optimize_attr"):
                        dst.optimize_attr = dict(src.optimize_attr)
        if for_test:
            p._set_inference_mode()
        return p

    def _set_inference_mode(self):
        """Flip train-only attrs (dropout/batch_norm `is_test`) and drop
        backward/optimize-role ops for eval clones (the reference strips by
        OpRole the same way, framework.py clone/_inference_optimize —
        without this, pruning an inference slice chases a parameter to its
        optimizer op's ParamOut and drags the whole training graph back in)."""
        self._is_inference = True
        for blk in self.blocks:
            blk.ops = [op for op in blk.ops
                       if op.attrs.get("__role__") not in ("backward",
                                                           "optimize")]
            for op in blk.ops:
                if "is_test" in op.attrs:
                    op.attrs["is_test"] = True
        self._bump()

    def _prune(self, targets: Sequence[str]) -> "Program":
        """Backward-slice the global block to ops needed for `targets`
        (reference: framework/prune.cc:181). A kept control-flow op keeps
        its whole sub-block tree, and the sub-blocks' external reads join
        the needed set — otherwise a While/StaticRNN body's producers in
        the global block would be mis-pruned."""
        p = self.clone()
        blk = p.global_block()
        needed = set(targets)
        keep: List[Operator] = []
        for op in reversed(blk.ops):
            if needed & set(op.output_arg_names) or op.type in ("feed", "fetch"):
                keep.append(op)
                needed |= set(op.input_arg_names)
                for si in sub_block_indices(op):
                    needed |= set(external_reads(p, si))
        blk.ops = list(reversed(keep))
        used = {n for op in blk.ops for n in op.input_arg_names + op.output_arg_names}
        for op in blk.ops:
            for si in sub_block_indices(op):
                used |= set(external_reads(p, si))
        blk.vars = {k: v for k, v in blk.vars.items() if k in used or v.persistable}
        p._bump()
        return p

    # -- serialization ----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"version": 1, "blocks": [b.to_dict() for b in self.blocks]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Program":
        p = cls()
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                vcls = Parameter if vd.get("is_parameter") else Variable
                kw = dict(shape=vd["shape"], dtype=vd["dtype"],
                          kind=VarKind(vd["kind"]), lod_level=vd["lod_level"],
                          persistable=vd["persistable"],
                          stop_gradient=vd["stop_gradient"])
                if vcls is Variable:
                    kw["is_data"] = vd.get("is_data", False)
                v = vcls(blk, vd["name"], **kw)
                if vcls is Parameter:
                    if vd.get("trainable") is not None:
                        v.trainable = vd["trainable"]
                    if vd.get("optimize_attr") is not None:
                        v.optimize_attr = vd["optimize_attr"]
                    if vd.get("sharding") is not None:
                        v.sharding = tuple(vd["sharding"])
                blk.vars[vd["name"]] = v
            for od in bd["ops"]:
                # capture_site=False: a deserialized op was not built here
                # — a captured site would point at whoever called
                # from_dict, which is noise (and a wasted frame walk/op)
                blk.ops.append(Operator(blk, od["type"], od["inputs"],
                                        od["outputs"], od["attrs"],
                                        capture_site=False))
            p.blocks.append(blk)
        p._current_block_idx = 0
        return p

    def serialize_to_string(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def parse_from_string(cls, s: str) -> "Program":
        return cls.from_dict(json.loads(s))

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def __repr__(self):
        lines = []
        for blk in self.blocks:
            lines.append(f"-- block {blk.idx} (parent {blk.parent_idx}) --")
            for op in blk.ops:
                lines.append(f"  {op}")
        return "\n".join(lines)


def sub_block_indices(op: Operator) -> List[int]:
    """Block indices referenced by a control-flow op's attrs."""
    out = []
    for key in ("sub_block", "else_block"):
        idx = op.attrs.get(key, -1)
        if isinstance(idx, int) and idx >= 0:
            out.append(idx)
    return out


def external_reads(program: "Program", block_idx: int) -> List[str]:
    """Variable names a block (and its nested blocks) reads from enclosing
    scopes: not block-local and not produced by an earlier op in the block.
    Used by executors for state analysis and by control-flow layers to
    declare data dependencies."""
    block = program.blocks[block_idx]
    produced: set = set()
    reads: List[str] = []
    for op in block.ops:
        in_names = list(op.input_arg_names)
        for si in sub_block_indices(op):
            in_names += external_reads(program, si)
        for n in in_names:
            if n in produced or n in block.vars or n in reads:
                continue
            reads.append(n)
        produced.update(op.output_arg_names)
    return reads


# ---------------------------------------------------------------------------
# Default program singletons + guards (reference framework.py:1843-1959).
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


class program_guard:
    """`with program_guard(main, startup):` context (reference framework.py:1911)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        self._prev_main = switch_main_program(self._main)
        if self._startup is not None:
            self._prev_startup = switch_startup_program(self._startup)
        return self

    def __exit__(self, *exc):
        switch_main_program(self._prev_main)
        if self._startup is not None:
            switch_startup_program(self._prev_startup)
        return False
