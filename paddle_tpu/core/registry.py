"""Op registry: each op type maps to a JAX lowering rule.

Capability parity with the reference's operator registry + kernel dispatch
(reference: paddle/fluid/framework/op_registry.h:185-217, op_info.h:68,
operator.cc:635-830). TPU-native redesign: an "op kernel" is a pure JAX
function (the *lowering rule*); whole blocks are traced through these rules
into a single XLA computation, so there is no per-op dispatch at runtime, no
OpKernelType keying, and no data-transform insertion — XLA owns layout/fusion.

Shape inference (reference shape_inference.h:30) is derived from the lowering
rule itself via `jax.eval_shape`: the rule is the single source of truth for
both compile-time shapes and runtime values.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import types

# Sentinel size substituted for -1 (unknown batch) dims during build-time shape
# inference. Prime and large, so it never collides with a real feature dim.
DIM_SENTINEL = 8191
# Second, distinct prime for the confirmation trace: a dim is dynamic iff it
# CHANGES when the sentinel changes (see infer_op_shapes). Divisibility alone
# cannot classify mixed derivations like concat(dynamic, static) = S+k.
DIM_SENTINEL_ALT = 7919

EMPTY_VAR = "@EMPTY@"
GRAD_OP_SUFFIX = "_grad"
FWD_OP_ATTR = "__fwd_op__"  # grad ops carry the forward OpDesc dict here


class OpDef:
    def __init__(self, type: str, lower: Callable, infer: Optional[Callable],
                 needs_rng: bool, propagate_seqlen: bool,
                 grad_lower: Optional[Callable] = None):
        self.type = type
        self.lower = lower
        self.infer = infer
        self.needs_rng = needs_rng
        self.propagate_seqlen = propagate_seqlen
        self.grad_lower = grad_lower
        # parameter names of the rule (minus ctx) = input slot names
        sig = inspect.signature(lower)
        params = list(sig.parameters.values())[1:]
        self.input_slots = [p.name for p in params]
        self.optional_slots = {p.name for p in params if p.default is not inspect.Parameter.empty}


_REGISTRY: Dict[str, OpDef] = {}


def register_op(type: str, infer: Optional[Callable] = None, needs_rng: bool = False,
                propagate_seqlen: bool = True):
    """Decorator registering a lowering rule for op `type`.

    The rule's signature is ``rule(ctx, SlotA, SlotB=None, ...)`` where slot
    parameter names match the OpDesc input slots; each receives a jnp array
    (or a list when the slot holds multiple vars, e.g. `sum`'s X). It returns
    ``{output_slot: array_or_list}``.
    """

    def deco(fn):
        if type in _REGISTRY:
            raise ValueError(f"op {type!r} already registered")
        _REGISTRY[type] = OpDef(type, fn, infer, needs_rng, propagate_seqlen)
        return fn

    return deco


def register_grad(type: str):
    """Optionally register a hand-written grad lowering for op `type`
    (overrides the generic vjp-based grad). Signature:
    ``grad(ctx, ins: dict, out_grads: dict) -> dict[input_slot, grad]``."""

    def deco(fn):
        if type not in _REGISTRY:
            close = close_op_names(type)
            hint = f"; closest registered: {', '.join(close)}" if close else ""
            raise ValueError(
                f"register_grad({type!r}): forward op {type!r} is not "
                f"registered — register_op must run first{hint}")
        _REGISTRY[type].grad_lower = fn
        return fn

    return deco


def close_op_names(name: str, n: int = 3) -> List[str]:
    """Registered op types most similar to `name` (typo hints for
    register_grad and the analysis verifier)."""
    import difflib
    return difflib.get_close_matches(name, _REGISTRY, n=n)


def get_op_def(type: str) -> OpDef:
    if type not in _REGISTRY:
        raise KeyError(f"op type {type!r} is not registered")
    return _REGISTRY[type]


def is_registered(type: str) -> bool:
    return type in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


class LoweringContext:
    """Per-op context handed to lowering rules.

    attrs: the OpDesc attrs; key: a PRNG key unique to (step, op position) for
    random ops, threaded functionally through the compiled step (replacing the
    reference's per-op cuRAND states).
    """

    # Sentinel the CURRENT abstract trace substituted for -1 dims: custom
    # `infer` rules must test dynamicness against this, not the module
    # constant (infer_op_shapes runs a second trace with DIM_SENTINEL_ALT
    # to tell sentinel-derived dims from real ones).
    dim_sentinel = DIM_SENTINEL

    def __init__(self, attrs: Dict[str, Any], key=None, lowerer=None, op=None,
                 env=None):
        self.attrs = attrs
        self.key = key
        self.lowerer = lowerer   # BlockLowerer, for control-flow sub-blocks
        self.op = op
        self.env = env           # live env dict (control-flow ops only)

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)


# AMP policy (torch-autocast style; reference analog:
# paddle/contrib/float16/float16_transpiler.py rewrote programs to fp16).
# MXU-heavy ops cast f32 inputs to bf16 and KEEP bf16 outputs — activations
# flow through the network in bf16 and never round-trip f32 in HBM (a cast
# feeding a conv cannot fuse on TPU, so per-op up/down-casts cost a full
# read+write of every activation).  Numerically sensitive ops upcast bf16
# inputs to f32.  Everything else runs in whatever dtype reaches it; the
# f32 master params are cast at their point of use, so the vjp delivers
# f32 grads to the optimizer automatically.
AMP_BF16_OPS = frozenset({"conv2d", "depthwise_conv2d", "conv2d_transpose",
                          "mul", "matmul", "lstm", "gru", "fc",
                          "fused_attention"})
# NOTE: plain `softmax` deliberately NOT f32-listed: jax.nn.softmax is
# max-subtracted so bf16 is safe, and an f32 round trip on [B,H,T,T]
# attention weights doubles the dominant HBM traffic of unfused attention.
# The loss-adjacent softmaxes (softmax_with_cross_entropy & co) stay f32.
AMP_F32_OPS = frozenset({"log_softmax", "cross_entropy",
                         "softmax_with_cross_entropy",
                         "sigmoid_cross_entropy_with_logits",
                         "square_error_cost", "smooth_l1", "huber_loss",
                         "mean", "reduce_mean", "nce", "hierarchical_sigmoid",
                         "linear_chain_crf", "warpctc", "cos_sim"})
# Mixed-dtype elementwise ops downcast the f32 side to bf16 instead of
# letting numpy promotion upcast the bf16 side: one f32 mask/bias/table
# leaking into the residual or attention-score stream would otherwise
# promote every downstream tensor to f32 and double its HBM traffic.
# bf16 keeps the full f32 exponent range, so additive masks (-1e9) and
# scales survive the downcast.
AMP_DOWNCAST_OPS = frozenset({"elementwise_add", "elementwise_sub",
                              "elementwise_mul", "elementwise_div",
                              "elementwise_max", "elementwise_min"})
# Back-compat alias (older tests/tools referenced AMP_OPS).
AMP_OPS = AMP_BF16_OPS


def _cast_to(v, dt_from, dt_to):
    if hasattr(v, "dtype") and v.dtype == dt_from:
        return v.astype(dt_to)
    return v


def call_rule(opdef: OpDef, ctx: LoweringContext, ins_by_slot: Dict[str, List[Any]]):
    """Dispatch arrays to the rule per its signature; normalize outputs."""
    amp_on = ctx.lowerer is not None and getattr(ctx.lowerer, "amp", False)
    to_bf16 = amp_on and opdef.type in AMP_BF16_OPS
    to_f32 = amp_on and opdef.type in AMP_F32_OPS
    if amp_on and not to_bf16 and not to_f32 and opdef.type in AMP_DOWNCAST_OPS:
        dtypes = {jnp.dtype(v.dtype)
                  for vals in ins_by_slot.values() for v in vals
                  if hasattr(v, "dtype")}
        to_bf16 = (jnp.dtype(jnp.bfloat16) in dtypes
                   and jnp.dtype(jnp.float32) in dtypes)
    kwargs = {}
    for slot in opdef.input_slots:
        vals = ins_by_slot.get(slot)
        if vals is None or len(vals) == 0:
            if slot not in opdef.optional_slots:
                raise ValueError(f"op {opdef.type}: required input slot {slot!r} missing")
            continue
        if to_bf16:
            vals = [_cast_to(v, jnp.float32, jnp.bfloat16) for v in vals]
        elif to_f32:
            vals = [_cast_to(v, jnp.bfloat16, jnp.float32) for v in vals]
        kwargs[slot] = vals[0] if len(vals) == 1 else list(vals)
    out = opdef.lower(ctx, **kwargs)
    if out is None:
        out = {}
    return {slot: (list(v) if isinstance(v, (list, tuple)) else [v])
            for slot, v in out.items()}


# ---------------------------------------------------------------------------
# Build-time shape inference via eval_shape (reference: InferShape contexts).
# ---------------------------------------------------------------------------

def _mark_dynamic(shape_a, shape_b):
    """Classify each output dim by comparing the two sentinel traces: a
    dim that moved when the sentinel moved derives from the dynamic input
    dim -> -1. This classifies EVERY arithmetic derivation — identity,
    multiples (flatten), and mixed sums like concat(dynamic, static) =
    S+k, which the old divisible-by-sentinel test left as a bogus
    concrete extent (e.g. 8194) that then poisoned downstream inference."""
    if shape_b is None:
        return tuple(int(d) for d in shape_a)
    return tuple(-1 if int(a) != int(b) else int(a)
                 for a, b in zip(shape_a, shape_b))


def _eval_abstract(opdef, attrs, ins_by_slot, sentinel):
    """One abstract trace with `sentinel` standing in for -1 dims.
    Returns {slot: [ShapeDtypeStruct, ...]}."""
    structs: Dict[str, List[jax.ShapeDtypeStruct]] = {}
    for slot, pairs in ins_by_slot.items():
        ss = []
        for shape, dtype in pairs:
            shp = [sentinel if d == -1 else int(d) for d in shape]
            ss.append(jax.ShapeDtypeStruct(tuple(shp), types.np_dtype(dtype)))
        structs[slot] = ss

    if opdef.infer is not None:
        ctx = LoweringContext(attrs)
        ctx.dim_sentinel = sentinel
        result = opdef.infer(ctx, structs)
    else:
        key = jax.random.key(0)

        def f(ins):
            ctx = LoweringContext(attrs, key=key)
            ctx.dim_sentinel = sentinel
            return call_rule(opdef, ctx, ins)

        result = jax.eval_shape(f, structs)
    return {slot: (list(vals) if isinstance(vals, (list, tuple)) else [vals])
            for slot, vals in result.items()}


# (op_type, attrs json, input signature) -> inferred result. Abstract
# traces are pure functions of the key, and model builders repeat
# identical layers (64 transformer blocks = 64x the same per-op shapes),
# so memoizing collapses the build-time cost of the two-sentinel scheme.
_infer_cache: Dict[tuple, Dict[str, List[tuple]]] = {}
_MAX_INFER_CACHE = 4096


def _infer_cache_key(op_type, attrs, ins_by_slot):
    import json
    try:
        akey = json.dumps(attrs, sort_keys=True, default=repr)
    except (TypeError, ValueError):  # unserializable attr -> don't cache
        return None
    sig = tuple(sorted((slot, tuple((tuple(s), str(d)) for s, d in pairs))
                       for slot, pairs in ins_by_slot.items()))
    return (op_type, akey, sig)


def infer_op_shapes(op_type: str, attrs: Dict[str, Any],
                    ins_by_slot: Dict[str, List[Any]]):
    """Return {output_slot: [(shape, dtype), ...]} for an op given input
    (shape, dtype) pairs. -1 dims are substituted with a sentinel and
    traced through the lowering rule abstractly; a second trace with a
    different sentinel identifies which output dims derive from the
    dynamic inputs (those map back to -1)."""
    opdef = get_op_def(op_type)
    key = _infer_cache_key(op_type, attrs, ins_by_slot)
    hit = _infer_cache.get(key) if key is not None else None
    if hit is not None:
        return {slot: list(pairs) for slot, pairs in hit.items()}
    had_dynamic = any(d == -1 for pairs in ins_by_slot.values()
                      for shape, _ in pairs for d in shape)
    result = _eval_abstract(opdef, attrs, ins_by_slot, DIM_SENTINEL)
    result_alt = (_eval_abstract(opdef, attrs, ins_by_slot, DIM_SENTINEL_ALT)
                  if had_dynamic else None)

    out = {}
    for slot, vals in result.items():
        alts = result_alt[slot] if result_alt is not None else [None] * len(vals)
        out[slot] = [(_mark_dynamic(v.shape, a.shape if a is not None else None),
                      types.canonical_dtype(v.dtype))
                     for v, a in zip(vals, alts)]
    if key is not None:
        if len(_infer_cache) >= _MAX_INFER_CACHE:
            _infer_cache.pop(next(iter(_infer_cache)))
        _infer_cache[key] = {slot: list(pairs) for slot, pairs in out.items()}
    return out
