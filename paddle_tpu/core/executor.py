"""Scope + Executor: run programs as single jit-compiled XLA steps.

Capability parity with the reference's Scope/Executor
(reference: paddle/fluid/framework/scope.h:39, executor.cc:294-366,
python/paddle/fluid/executor.py:224-470).

TPU-native redesign: the reference interprets ops one by one against a
mutable Scope, syncing the device every run (executor.cc:345). Here the
executor lowers the whole block to ONE pure jitted function
`(feeds, mutable_state, const_state, key) -> (fetches, new_mutable_state)`,
compiled once per (program version, feed signature) and cached — the XLA
analog of the reference's `Prepare`/`RunPreparedContext` program cache.
Mutable state (parameters, optimizer accumulators) is donated to XLA so
updates are in-place in HBM; there is no per-step host sync and no per-op
dispatch.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import ir, registry
from .lowering import BlockLowerer

logger = logging.getLogger(__name__)


class EOFException(Exception):
    """Raised when a py_reader-fed program drains its queue (reference:
    paddle/fluid/framework/reader.h EOF semantics surfaced as
    core.EOFException in python)."""


# ---------------------------------------------------------------------------
# Places (reference: platform/place.h). On TPU these are thin shims over jax
# devices; XLA/PJRT owns device memory and streams.
# ---------------------------------------------------------------------------

class Place:
    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def jax_device(self):
        # local_devices: under multi-controller jax, jax.devices()[0] can
        # belong to ANOTHER process — computing there would leave this
        # process holding arrays with no addressable shards
        for d in jax.local_devices():
            if d.platform == "cpu":
                return d
        return jax.local_devices()[0]

    def __repr__(self):
        return "CPUPlace()"


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Alias so reference scripts using CUDAPlace keep working on TPU.
CUDAPlace = TPUPlace


class Scope:
    """Hierarchical name -> array holder (reference scope.h:39)."""

    _uid_counter = itertools.count()

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List[Scope] = []
        # process-unique id for executor cache keys (id() recycles after GC)
        self._uid = next(Scope._uid_counter)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def var(self, name: str):
        """Get a variable from THIS scope only (no parent lookup); returns
        None if absent. Unlike the reference's Scope::Var this does not
        create — arrays are materialized by programs, use set_var."""
        return self._vars.get(name)

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def set_var(self, name: str, value):
        self._vars[name] = value

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def erase(self, names: Sequence[str]):
        for n in names:
            self._vars.pop(n, None)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _as_feed_array(v, var: Optional[ir.Variable]):
    if isinstance(v, jax.Array):
        # already on device (e.g. AsyncFeeder pre-transfer) — never round-trip
        # through host
        return v
    arr = np.asarray(v)
    if var is not None and var.dtype and arr.dtype != jnp.dtype(var.dtype):
        # Follow the reference DataFeeder's implicit cast for python scalars.
        if arr.dtype.kind in "fiub":
            arr = arr.astype(jnp.dtype(var.dtype))
    return arr


def resolve_compiler_options(platform: str, program=None):
    """Per-executable XLA options from the `xla_compiler_options` flag.

    "auto" applies the measured-good TPU set from the round-5 compiler
    flag sweep (docs/PERF.md): a 32 MiB scoped-VMEM budget lets the
    fusion merger form larger fusions (fewer HBM round-trips between
    them) — worth ~9% end-to-end on transformer-base. The same budget
    measured ~7% SLOWER on ResNet-50 (conv fusions are already at the
    HBM roofline; the bigger budget regroups them badly), so "auto"
    applies only to conv-free programs — the boundary the interleaved
    A/Bs actually support. An explicit k=v list applies unconditionally.
    Non-TPU backends get None (the names are TPU-only and other backends
    reject unknown options)."""
    from .. import flags as _flags

    val = _flags.get_flag("xla_compiler_options")
    if val == "auto":
        if platform != "tpu":
            return None
        if program is not None and _program_has_conv(program):
            return None
        return {"xla_tpu_scoped_vmem_limit_kib": "32768"}
    if not val or val == "none":
        return None
    return dict(kv.split("=", 1) for kv in val.split(",") if kv)


_has_conv_cache: Dict[tuple, bool] = {}


def _program_has_conv(program) -> bool:
    """Memoized per (program uid, version): run() calls this every step
    and a full op walk on a large program is avoidable repeated work."""
    key = (program._uid, program._version)
    hit = _has_conv_cache.get(key)
    if hit is None:
        hit = any("conv" in op.type
                  for block in program.blocks for op in block.ops)
        _has_conv_cache[key] = hit
    return hit


class _CompiledProgram:
    """One lowered+jitted step for a (program version, feed/fetch set)."""

    def __init__(self, program: ir.Program, feed_names, fetch_names, scope: Scope,
                 donate: bool, amp: bool = False, check_nan_inf: bool = False,
                 mesh=None, compiler_options=None, rng_stream: int = 0):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.check_nan_inf = check_nan_inf
        self._nan_meta = []
        block = program.global_block()
        lowerer = BlockLowerer(program, amp=amp, check_nan_inf=check_nan_inf,
                               mesh=mesh)

        # Statically determine which scope vars the block reads/writes.
        written: List[str] = []
        produced = set(self.feed_names)
        read: List[str] = []
        for op in block.ops:
            in_names = list(op.input_arg_names)
            for si in ir.sub_block_indices(op):
                in_names += ir.external_reads(program, si)
            for n in in_names:
                if n == registry.EMPTY_VAR:
                    continue
                if n not in produced and n not in read:
                    read.append(n)
            for n in op.output_arg_names:
                if n == registry.EMPTY_VAR:
                    continue
                produced.add(n)
                # runtime seqlen propagation (lowering.py) materializes the
                # @SEQLEN companion of sequence outputs without an explicit op
                produced.add(n + ir.SEQLEN_SUFFIX)
                produced.add(n + ir.SEQLEN_SUFFIX + ".1")
                v = block._find_var_recursive(n)
                if v is not None and v.persistable and n not in written:
                    written.append(n)
        missing = [n for n in read if not scope.has_var(n)]
        if missing:
            missing_data = [n for n in missing
                            if (v := block._find_var_recursive(n)) is not None and v.is_data]
            if missing_data:
                raise RuntimeError(
                    f"input variables {missing_data} were not fed — pass them in "
                    f"`feed={{...}}`")
            raise RuntimeError(
                f"variables {missing} are read by the program but not initialized "
                f"in the scope — run the startup program first")
        self.state_read = read
        self.state_written = written
        self.mut_names = [n for n in read if n in set(written)]
        self.const_names = [n for n in read if n not in set(written)]
        self.new_names = [n for n in written if n not in set(read)]

        seed = program.random_seed if program.random_seed is not None else 0
        # unseeded programs additionally fold in their executor-local
        # ordinal (`rng_stream`): with the per-program run counters, two
        # distinct unseeded programs run through ONE executor would
        # otherwise draw IDENTICAL key sequences (fold_in(key(0), 0..n))
        # and e.g. correlate their dropout masks (round-4 advisor). The
        # ordinal — not the global program uid — keeps the stream
        # deterministic for a given executor's usage pattern regardless
        # of how many programs OTHER code built first. Explicitly seeded
        # programs keep the pure-counter derivation — that is the
        # cross-executor reproducibility contract.
        uid_mix = None if program.random_seed is not None or not rng_stream \
            else np.uint32(rng_stream)

        def step(feeds, mut_state, const_state, counter):
            # key derivation INSIDE the jit: an eager fold_in would
            # dispatch 2-4 tiny device programs per run (visible in the
            # profiler as jit__threefry_* modules), pure host overhead
            key = jax.random.fold_in(jax.random.key(seed), counter)
            if uid_mix is not None:
                key = jax.random.fold_in(key, uid_mix)
            env = {}
            env.update(const_state)
            env.update(mut_state)
            env.update(feeds)
            lowerer.nan_flags = []
            lowerer.run_block(0, env, key)
            fetches = [env[n] for n in self.fetch_names]
            new_state = {n: env[n] for n in written if n in env}
            # trace-time side effect: remember which (op, var) each flag
            # belongs to so the host can name the offender
            self._nan_meta = [(t, n) for t, n, _ in lowerer.nan_flags]
            flags = ([f for _, _, f in lowerer.nan_flags]
                     if lowerer.check_nan_inf else [])
            return fetches, new_state, flags

        donate_args = (1,) if donate else ()
        self._step = jax.jit(step, donate_argnums=donate_args,
                             compiler_options=compiler_options or None)

    def run(self, scope: Scope, feeds: Dict[str, Any], counter):
        mut = {n: scope.find_var(n) for n in self.mut_names}
        const = {n: scope.find_var(n) for n in self.const_names}
        fetches, new_state, flags = self._step(feeds, mut, const, counter)
        for n, v in new_state.items():
            scope.set_var(n, v)
        if self.check_nan_inf and flags:
            finite = np.asarray(jnp.stack(flags))
            if not finite.all():
                bad = int(np.argmin(finite))
                op_type, var = self._nan_meta[bad]
                raise RuntimeError(
                    f"NaN/Inf detected in output {var!r} of op "
                    f"{op_type!r} (check_nan_inf mode; reference "
                    f"CheckTensorNANOrInf, operator.cc:622)")
        return fetches


class Executor:
    """Program runner (reference executor.py:224).

    `place` selects the device; `exe.run(program, feed=..., fetch_list=...)`
    matches the reference API. Programs are compiled on first run and cached.
    """

    def __init__(self, place: Optional[Place] = None, amp: bool = False,
                 check_nan_inf: Optional[bool] = None):
        self.place = place or TPUPlace(0)
        self.amp = amp  # bf16 mixed precision (reference float16_transpiler analog)
        # debug mode: per-op finite checks (reference FLAGS_check_nan_inf).
        # None = follow the flag registry at run time, so
        # set_flag("check_nan_inf", True) takes effect on the next run
        # (a new cache entry compiles with the checks baked in).
        self._check_nan_inf = check_nan_inf
        self._cache: Dict[tuple, _CompiledProgram] = {}
        self._run_counts: Dict[int, int] = {}  # program uid -> runs so far
        self._prog_order: Dict[int, int] = {}  # program uid -> ordinal

    @property
    def check_nan_inf(self) -> bool:
        if self._check_nan_inf is None:
            from .. import flags as _flags
            return _flags.get_flag("check_nan_inf")
        return self._check_nan_inf

    @check_nan_inf.setter
    def check_nan_inf(self, value):
        self._check_nan_inf = value

    def run(self,
            program: Optional[ir.Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[Union[str, ir.Variable]]] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        program = program or ir.default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        # A pserver program (one listen_and_serv op) is a HOST service, not
        # an XLA computation: serve until stopped, exactly like the
        # reference's blocking Executor.run on the pserver program
        # (reference listen_and_serv_op.cc:267).
        ls = [op for op in program.global_block().ops
              if op.type == "listen_and_serv"]
        if ls:
            from ..pserver.server import ParameterServer
            ps = ParameterServer(ls[0].attrs["endpoint"],
                                 trainers=ls[0].attrs.get("trainers", 1))
            ps.serve_forever()
            return []

        # py_reader-fed program: no feed -> pop the next queued batch
        # (raises EOFException at end of pass, reference read-op contract)
        if not feed and getattr(program, "_py_reader", None) is not None:
            feed = program._py_reader.next_feed()
        fetch_names = [f.name if isinstance(f, ir.Variable) else str(f)
                       for f in fetch_list]

        block = program.global_block()
        feed_arrays = {}
        for name, val in feed.items():
            var = block.vars.get(name)
            if isinstance(val, (tuple, list)) and len(val) == 2 and var is not None \
                    and var.lod_level > 0:
                data, lens = val
                feed_arrays[name] = _as_feed_array(data, var)
                if isinstance(lens, (tuple, list)) and len(lens) == 2 \
                        and not np.isscalar(lens[0]):
                    # nested LoD: (outer counts [B], inner lengths [B, S])
                    feed_arrays[ir.seqlen_var_name(name)] = \
                        np.asarray(lens[0], np.int32)
                    feed_arrays[ir.seqlen_var_name(name, 1)] = \
                        np.asarray(lens[1], np.int32)
                else:
                    feed_arrays[ir.seqlen_var_name(name)] = \
                        np.asarray(lens, np.int32)
            else:
                feed_arrays[name] = _as_feed_array(val, var)

        from .. import flags as _flags
        copts = resolve_compiler_options(self.place.jax_device().platform,
                                         program)
        cache_key = (program._uid, program._version,
                     tuple(sorted(feed_arrays)), tuple(fetch_names),
                     scope._uid, self.amp, self.check_nan_inf,
                     _flags.get_flag("dropout_impl"),
                     tuple(sorted(copts.items())) if copts else None,
                     program.random_seed)  # seed is baked into the trace
        stream = self._prog_order.setdefault(program._uid,
                                             len(self._prog_order))
        compiled = self._cache.get(cache_key) if use_program_cache else None
        if compiled is None:
            with jax.default_device(self.place.jax_device()):
                compiled = _CompiledProgram(program, sorted(feed_arrays),
                                            fetch_names, scope, donate=True,
                                            amp=self.amp,
                                            check_nan_inf=self.check_nan_inf,
                                            compiler_options=copts,
                                            rng_stream=stream)
            if use_program_cache:
                self._cache[cache_key] = compiled

        # PER-PROGRAM run counter: the PRNG key is fold_in(key(seed),
        # runs-of-THIS-program), so a seeded startup re-initializes
        # identically no matter what else this executor ran (cross-
        # executor/mesh parity), while seeded TRAINING still draws a
        # fresh-but-reproducible mask every step (reference random_seed
        # reproducibility with per-step variation — the round-3 dropout
        # contract, tests/test_amp_perf_ops.py)
        counter = np.uint32(self._run_counts.get(program._uid, 0))
        self._run_counts[program._uid] = int(counter) + 1
        with jax.default_device(self.place.jax_device()):
            fetches = compiled.run(scope, feed_arrays, counter)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    def close(self):
        self._cache.clear()


import contextlib as _contextlib


def _switch_scope(scope: Scope) -> Scope:
    """Swap the process-global scope, returning the previous one
    (reference executor.py _switch_scope)."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    return prev


@_contextlib.contextmanager
def scope_guard(scope: Scope):
    """Run a `with` region against `scope` as the global scope (reference
    executor.py scope_guard)."""
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)


def fetch_var(name: str, scope: Optional[Scope] = None, return_numpy: bool = True):
    """Read a variable's current value from a scope (reference
    executor.py fetch_var)."""
    scope = scope or global_scope()
    val = scope.find_var(name)
    if val is None:
        raise KeyError(f"fetch_var: variable {name!r} not found in scope")
    return np.asarray(val) if return_numpy else val
