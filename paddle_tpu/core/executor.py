"""Scope + Executor: run programs as single jit-compiled XLA steps.

Capability parity with the reference's Scope/Executor
(reference: paddle/fluid/framework/scope.h:39, executor.cc:294-366,
python/paddle/fluid/executor.py:224-470).

TPU-native redesign: the reference interprets ops one by one against a
mutable Scope, syncing the device every run (executor.cc:345). Here the
executor lowers the whole block to ONE pure jitted function
`(feeds, mutable_state, const_state, key) -> (fetches, new_mutable_state)`,
compiled once per (program version, feed signature) and cached — the XLA
analog of the reference's `Prepare`/`RunPreparedContext` program cache.
Mutable state (parameters, optimizer accumulators) is donated to XLA so
updates are in-place in HBM; there is no per-step host sync and no per-op
dispatch.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import ir, registry
from .. import flags as _flags
from ..observe import steplog as _steplog
from .lowering import BlockLowerer

logger = logging.getLogger(__name__)


class EOFException(Exception):
    """Raised when a py_reader-fed program drains its queue (reference:
    paddle/fluid/framework/reader.h EOF semantics surfaced as
    core.EOFException in python)."""


# ---------------------------------------------------------------------------
# Places (reference: platform/place.h). On TPU these are thin shims over jax
# devices; XLA/PJRT owns device memory and streams.
# ---------------------------------------------------------------------------

class Place:
    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def jax_device(self):
        # local_devices: under multi-controller jax, jax.devices()[0] can
        # belong to ANOTHER process — computing there would leave this
        # process holding arrays with no addressable shards
        for d in jax.local_devices():
            if d.platform == "cpu":
                return d
        return jax.local_devices()[0]

    def __repr__(self):
        return "CPUPlace()"


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def jax_device(self):
        devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# Alias so reference scripts using CUDAPlace keep working on TPU.
CUDAPlace = TPUPlace


class Scope:
    """Hierarchical name -> array holder (reference scope.h:39).

    Mutations bump a version counter shared by the whole scope TREE (kept
    on the root): prepared programs cache their state gather against it,
    and find_var walks parents, so a parent mutation must invalidate a
    child-bound cache too. One counter per tree (not per process) keeps
    independent scopes from invalidating each other's caches."""

    _uid_counter = itertools.count()

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List[Scope] = []
        # process-unique id for executor cache keys (id() recycles after GC)
        self._uid = next(Scope._uid_counter)
        self._root = parent._root if parent is not None else self
        if parent is None:
            self._version = 0

    def version(self) -> int:
        return self._root._version

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []
        self._root._version += 1

    def var(self, name: str):
        """Get a variable from THIS scope only (no parent lookup); returns
        None if absent. Unlike the reference's Scope::Var this does not
        create — arrays are materialized by programs, use set_var."""
        return self._vars.get(name)

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def set_var(self, name: str, value):
        self._vars[name] = value
        self._root._version += 1

    def has_var(self, name: str) -> bool:
        return self.find_var(name) is not None

    def local_var_names(self) -> List[str]:
        return list(self._vars)

    def erase(self, names: Sequence[str]):
        for n in names:
            self._vars.pop(n, None)
        self._root._version += 1


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _as_feed_array(v, var: Optional[ir.Variable]):
    if isinstance(v, jax.Array):
        # already on device (e.g. AsyncFeeder pre-transfer) — never round-trip
        # through host
        return v
    arr = np.asarray(v)
    if var is not None and var.dtype and arr.dtype != jnp.dtype(var.dtype):
        # Follow the reference DataFeeder's implicit cast for python scalars.
        if arr.dtype.kind in "fiub":
            arr = arr.astype(jnp.dtype(var.dtype))
    return arr


def _convert_feed_dict(block, feed: Dict[str, Any]) -> Dict[str, Any]:
    """User feed dict -> array dict, materializing @SEQLEN companions for
    (data, lengths) LoD feeds. Shared by the unprepared and prepared paths
    so both produce identical feed signatures."""
    feed_arrays = {}
    for name, val in feed.items():
        var = block.vars.get(name)
        if isinstance(val, (tuple, list)) and len(val) == 2 and var is not None \
                and var.lod_level > 0:
            data, lens = val
            feed_arrays[name] = _as_feed_array(data, var)
            if isinstance(lens, (tuple, list)) and len(lens) == 2 \
                    and not np.isscalar(lens[0]):
                # nested LoD: (outer counts [B], inner lengths [B, S])
                feed_arrays[ir.seqlen_var_name(name)] = \
                    np.asarray(lens[0], np.int32)
                feed_arrays[ir.seqlen_var_name(name, 1)] = \
                    np.asarray(lens[1], np.int32)
            else:
                feed_arrays[ir.seqlen_var_name(name)] = \
                    np.asarray(lens, np.int32)
        else:
            feed_arrays[name] = _as_feed_array(val, var)
    return feed_arrays


class _StateCache:
    """Scope-version-keyed cache of a compiled step's (mut, const) state
    gather. The gather is O(state vars) of find_var walks — pure per-step
    host overhead once the program is steady — so it is rebuilt only when
    the scope tree reports a mutation the executor didn't make itself."""

    def __init__(self):
        self._entry = None
        self._version = -1
        self._mut: Optional[Dict[str, Any]] = None
        self._const: Optional[Dict[str, Any]] = None

    def get(self, entry: "_CompiledProgram", scope: Scope):
        if (entry is not self._entry or self._mut is None
                or scope.version() != self._version):
            self._mut, self._const = entry.gather_state(scope)
            self._entry = entry
        return self._mut, self._const

    def commit(self, entry: "_CompiledProgram", scope: Scope, new_state):
        """Refresh after a step: the mut arrays were donated (dead); swap
        in the step's outputs, then adopt the scope version the write-back
        produced so our own set_var calls don't invalidate the cache."""
        mut = self._mut
        for n in entry.mut_names:
            v = new_state.get(n)
            if v is not None:
                mut[n] = v
        self._version = scope.version()


def resolve_compiler_options(platform: str, program=None):
    """Per-executable XLA options from the `xla_compiler_options` flag.

    "auto" applies the measured-good TPU set from the round-5 compiler
    flag sweep (docs/PERF.md): a 32 MiB scoped-VMEM budget lets the
    fusion merger form larger fusions (fewer HBM round-trips between
    them) — worth ~9% end-to-end on transformer-base. The same budget
    measured ~7% SLOWER on ResNet-50 (conv fusions are already at the
    HBM roofline; the bigger budget regroups them badly), so "auto"
    applies only to conv-free programs — the boundary the interleaved
    A/Bs actually support. An explicit k=v list applies unconditionally.
    Non-TPU backends get None (the names are TPU-only and other backends
    reject unknown options)."""
    val = _flags.get_flag("xla_compiler_options")
    if val == "auto":
        if platform != "tpu":
            return None
        if program is not None and _program_has_conv(program):
            return None
        return {"xla_tpu_scoped_vmem_limit_kib": "32768"}
    if not val or val == "none":
        return None
    opts = {}
    for kv in val.split(","):
        if not kv:
            continue
        if "=" not in kv:
            raise ValueError(
                f"xla_compiler_options entry {kv!r} is malformed — expected "
                f"'name=value' pairs separated by commas (full flag value: "
                f"{val!r})")
        k, v = kv.split("=", 1)
        opts[k] = v
    return opts


# program uid -> (program version, has_conv). Keyed by uid with the version
# INSIDE the value so a mutated program replaces its stale entry instead of
# accreting one per version in a long-lived process.
_has_conv_cache: Dict[int, tuple] = {}


def _program_has_conv(program) -> bool:
    """Memoized per program uid (latest version wins): run() calls this on
    bind and a full op walk on a large program is avoidable repeated work."""
    hit = _has_conv_cache.get(program._uid)
    if hit is None or hit[0] != program._version:
        val = any("conv" in op.type
                  for block in program.blocks for op in block.ops)
        if hit is None and len(_has_conv_cache) >= _MAX_TRACKED_PROGRAMS:
            _has_conv_cache.pop(next(iter(_has_conv_cache)))
        _has_conv_cache[program._uid] = (program._version, val)
        return val
    return hit[1]


def donation_safe() -> bool:
    """Whether donate_argnums may be used for compiled steps.

    Buffer donation and the persistent compilation cache are MUTUALLY
    EXCLUSIVE on this jaxlib's CPU backend: a warm-cache hit of a
    donate_argnums executable loses its input-output aliasing on
    deserialization and reuses the donated buffers while still
    referenced — a use-after-free that bus-errors, segfaults, or
    silently corrupts the carried state (minimal repro: a donated jit
    run twice across processes against one cache dir; without donation
    the same cache is bit-deterministic). Donated mutable state is a
    core perf design (in-place HBM updates), so instead of banning the
    cache, the executor drops donation whenever a compilation cache dir
    is configured on a CPU backend — the cache is a test/dev iteration
    lever (tests/conftest.py), never configured on the TPU
    serving/training path, which keeps full donation."""
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
    except AttributeError:
        return True
    return not cache_dir or jax.default_backend() != "cpu"


class _CompiledProgram:
    """One lowered+jitted step for a (program version, feed/fetch set)."""

    def __init__(self, program: ir.Program, feed_names, fetch_names, scope: Scope,
                 donate: bool, amp: bool = False, check_nan_inf: bool = False,
                 mesh=None, compiler_options=None, rng_stream: int = 0):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.check_nan_inf = check_nan_inf
        self._nan_meta = []
        block = program.global_block()
        lowerer = BlockLowerer(program, amp=amp, check_nan_inf=check_nan_inf,
                               mesh=mesh)

        # Statically determine which scope vars the block reads/writes.
        written: List[str] = []
        produced = set(self.feed_names)
        read: List[str] = []
        for op in block.ops:
            in_names = list(op.input_arg_names)
            for si in ir.sub_block_indices(op):
                in_names += ir.external_reads(program, si)
            for n in in_names:
                if n == registry.EMPTY_VAR:
                    continue
                if n not in produced and n not in read:
                    read.append(n)
            for n in op.output_arg_names:
                if n == registry.EMPTY_VAR:
                    continue
                produced.add(n)
                # runtime seqlen propagation (lowering.py) materializes the
                # @SEQLEN companion of sequence outputs without an explicit op
                produced.add(n + ir.SEQLEN_SUFFIX)
                produced.add(n + ir.SEQLEN_SUFFIX + ".1")
                v = block._find_var_recursive(n)
                if v is not None and v.persistable and n not in written:
                    written.append(n)
        missing = [n for n in read if not scope.has_var(n)]
        if missing:
            missing_data = [n for n in missing
                            if (v := block._find_var_recursive(n)) is not None and v.is_data]
            if missing_data:
                raise RuntimeError(
                    f"input variables {missing_data} were not fed — pass them in "
                    f"`feed={{...}}`")
            raise RuntimeError(
                f"variables {missing} are read by the program but not initialized "
                f"in the scope — run the startup program first")
        self.state_read = read
        self.state_written = written
        self.mut_names = [n for n in read if n in set(written)]
        self.const_names = [n for n in read if n not in set(written)]
        self.new_names = [n for n in written if n not in set(read)]

        seed = program.random_seed if program.random_seed is not None else 0
        # unseeded programs additionally fold in their executor-local
        # ordinal (`rng_stream`): with the per-program run counters, two
        # distinct unseeded programs run through ONE executor would
        # otherwise draw IDENTICAL key sequences (fold_in(key(0), 0..n))
        # and e.g. correlate their dropout masks (round-4 advisor). The
        # ordinal — not the global program uid — keeps the stream
        # deterministic for a given executor's usage pattern regardless
        # of how many programs OTHER code built first. Explicitly seeded
        # programs keep the pure-counter derivation — that is the
        # cross-executor reproducibility contract.
        uid_mix = None if program.random_seed is not None or not rng_stream \
            else np.uint32(rng_stream)

        def step(feeds, mut_state, const_state, counter):
            # key derivation INSIDE the jit: an eager fold_in would
            # dispatch 2-4 tiny device programs per run (visible in the
            # profiler as jit__threefry_* modules), pure host overhead
            key = jax.random.fold_in(jax.random.key(seed), counter)
            if uid_mix is not None:
                key = jax.random.fold_in(key, uid_mix)
            env = {}
            env.update(const_state)
            env.update(mut_state)
            env.update(feeds)
            lowerer.nan_flags = []
            lowerer.run_block(0, env, key)
            fetches = [env[n] for n in self.fetch_names]
            new_state = {n: env[n] for n in written if n in env}
            # trace-time side effect: remember which (op, var) each flag
            # belongs to so the host can name the offender
            self._nan_meta = [(t, n) for t, n, _ in lowerer.nan_flags]
            flags = ([f for _, _, f in lowerer.nan_flags]
                     if lowerer.check_nan_inf else [])
            return fetches, new_state, flags

        donate_args = (1,) if donate and donation_safe() else ()
        self._step = jax.jit(step, donate_argnums=donate_args,
                             compiler_options=compiler_options or None)

    def gather_state(self, scope: Scope):
        mut = {n: scope.find_var(n) for n in self.mut_names}
        const = {n: scope.find_var(n) for n in self.const_names}
        return mut, const

    def run(self, scope: Scope, feeds: Dict[str, Any], counter):
        mut, const = self.gather_state(scope)
        return self.run_with_state(scope, feeds, mut, const, counter)[0]

    def run_with_state(self, scope: Scope, feeds, mut, const, counter):
        """One step against pre-gathered state dicts; returns (fetches,
        new_state) so callers holding a state cache can refresh their mut
        entries (the mut arrays were donated to XLA and are dead after the
        call)."""
        fetches, new_state, flags = self._step(feeds, mut, const, counter)
        # bulk write-back: one dict update + one version bump (set_var per
        # name costs ~10µs/step on wide optimizers; equality-based cache
        # invalidation only needs the version to CHANGE, not count)
        scope._vars.update(new_state)
        scope._root._version += 1
        if self.check_nan_inf and flags:
            finite = np.asarray(jnp.stack(flags))
            if not finite.all():
                bad = int(np.argmin(finite))
                op_type, var = self._nan_meta[bad]
                raise RuntimeError(
                    f"NaN/Inf detected in output {var!r} of op "
                    f"{op_type!r} (check_nan_inf mode; reference "
                    f"CheckTensorNANOrInf, operator.cc:622)")
        return fetches, new_state


# leak backstop for the per-program uid maps (run counters / rng ordinals):
# a long-lived process churning through distinct Program objects stops
# growing them past this. Evicting a counter only matters if that exact
# program runs AGAIN later (its unseeded rng stream restarts), which after
# 4096 intervening programs is a serving process recycling graphs, not a
# training loop.
_MAX_TRACKED_PROGRAMS = 4096

# run()'s PreparedProgram memo cap: unlike the compile cache (whose
# entries hold no arrays), a prepared handle pins its scope and the
# gathered state dicts, so the memo is kept small — steady-state loops
# use only a few handles, and rebuilding an evicted one is cheap.
_MAX_PREPARED_HANDLES = 64


def _evict_stale_versions(cache: Dict[tuple, Any], uid: int, version: int):
    """Drop cache entries for older versions of a (mutated) program before
    inserting the current version's — keyed caches would otherwise grow one
    entry per mutation in long-lived processes (advisor r5). Keys must lead
    with (program uid, program version)."""
    stale = [k for k in cache if k[0] == uid and k[1] != version]
    for k in stale:
        del cache[k]


def _evict_superseded(cache: Dict[tuple, Any], key: tuple, prefix: int = 4):
    """Drop memo entries that agree with `key` on its first `prefix`
    fields but differ beyond them (a flag flip re-keys the memo for the
    same program/feed/fetch/scope — the superseded entry would otherwise
    leak one handle per flip)."""
    stale = [k for k in cache if k[:prefix] == key[:prefix] and k != key]
    for k in stale:
        del cache[k]


# (program uid, version, feed/fetch sig, mode) keys already validated:
# Executor.run rebuilds PreparedProgram handles on scope churn / flag
# flips / memo eviction, and re-sweeping an unchanged program each time
# would defeat PR 1's cheap-rebuild contract. Errors are never cached
# (they raise); a mutation bumps the version and re-validates.
_validated: Dict[tuple, bool] = {}


def _validate_program(program, mode, feed_names, fetch_names):
    """`validate` hook shared by prepare()/run(): mode None follows the
    `validate` flag; "error" raises ProgramVerificationError on ERROR
    findings, "warn" logs everything found (once per program version),
    "off" is free."""
    if mode is None:
        mode = _flags.get_flag("validate")
    if mode == "off":
        return
    if mode not in ("error", "warn"):
        raise ValueError(f"validate must be 'error', 'warn' or 'off', "
                         f"got {mode!r}")
    key = (program._uid, program._version,
           tuple(feed_names or ()), tuple(fetch_names or ()), mode)
    if _validated.get(key):
        return
    from .. import analysis
    # listen_and_serv programs are host services, not XLA computations
    if not any(op.type == "listen_and_serv"
               for op in program.global_block().ops):
        diags = analysis.analyze_program(program, feed_targets=feed_names,
                                         fetch_targets=fetch_names or None,
                                         lint=(mode == "warn"))
        if mode == "error" and analysis.has_errors(diags):
            raise analysis.ProgramVerificationError(diags)
        if diags:
            logger.warning("program validation findings:\n%s",
                           analysis.format_diagnostics(diags))
    _evict_stale_versions(_validated, program._uid, program._version)
    if len(_validated) >= _MAX_TRACKED_PROGRAMS:
        _validated.pop(next(iter(_validated)))
    _validated[key] = True


class PreparedProgram:
    """Bound fast-path handle from `Executor.prepare()` (reference
    Executor::Prepare / RunPreparedContext, executor.cc:294-366; TF's
    session-handle design serves the same purpose).

    Everything resolvable once per (program, fetch list, scope) — compiler
    options, flag reads, the listen_and_serv scan, fetch-name resolution —
    happens at construction; the compiled entry binds lazily on the first
    `run(feed)` (the feed signature, including @SEQLEN companions, is only
    knowable from real feed values). After that, each `run(feed)` does
    only: feed conversion, a scope-version-checked cached state gather, the
    jitted call, and state write-back. `return_numpy=False` returns the
    step's `jax.Array` outputs without forcing a host sync, so dispatch of
    the next step overlaps this step's device execution."""

    def __init__(self, executor: "Executor", program: ir.Program,
                 fetch_list, scope: Scope, feed_names=None, validate=None):
        self._exe = executor
        self.program = program
        self.fetch_names = [f.name if isinstance(f, ir.Variable) else str(f)
                            for f in (fetch_list or [])]
        self.feed_names = list(feed_names) if feed_names else None
        self.scope = scope
        self._block = program.global_block()
        # flag-gated static verification (analysis/): runs HERE, before
        # any lowering — a malformed program is rejected with op
        # provenance instead of a tracer error inside XLA at first run
        _validate_program(program, validate, self.feed_names,
                          self.fetch_names)
        self._device = executor.place.jax_device()
        self._program_version = program._version
        # flag-derived settings are baked at bind time; Executor.run's memo
        # keys on the flag-registry version, so a set_flag() flip yields a
        # fresh handle on the next run() (direct handle holders keep the
        # settings they prepared with — re-prepare to pick up flag flips)
        self._check_nan_inf = executor.check_nan_inf
        self._dropout_impl = _flags.get_flag("dropout_impl")
        self._copts = resolve_compiler_options(self._device.platform, program)
        ls = [op for op in self._block.ops if op.type == "listen_and_serv"]
        self._serve_attrs = ls[0].attrs if ls else None
        # telemetry attribution: the serving layer (serve/) re-tags its
        # handles "serving" so step stats and compile events separate
        # request traffic from training, and shape misses attribute as
        # `padding_bucket` (mis-sized bucket ladder) not `feed_shape`
        self.telemetry_source = "executor"
        self._entries: Dict[tuple, _CompiledProgram] = {}
        self._entry: Optional[_CompiledProgram] = None
        self._entry_keys = frozenset()
        self._feed_plan = None   # bound by _bind (per-name dtype plan)
        self._plan_keys = frozenset()
        self._state = _StateCache()
        # entering jax.default_device() per step costs ~hundreds of µs
        # (the config context defeats pjit's C++ fast path). Steps that
        # read ANY scope state don't need it: the state arrays were
        # committed to the right device at startup/bind, and committed
        # args pin the execution device. Only an all-feed (stateless)
        # step, whose numpy args would follow jax's global default,
        # keeps the context.
        self._use_device_ctx = True

    @property
    def device(self):
        """The jax device this handle dispatches to (AsyncFeeder targets
        pre-step transfers here)."""
        return self._device

    def run(self, feed: Optional[Dict[str, Any]] = None,
            return_numpy: bool = True):
        # A pserver program (one listen_and_serv op) is a HOST service, not
        # an XLA computation: serve until stopped, exactly like the
        # reference's blocking Executor.run on the pserver program
        # (reference listen_and_serv_op.cc:267).
        if self._serve_attrs is not None:
            from ..pserver.server import ParameterServer
            ps = ParameterServer(self._serve_attrs["endpoint"],
                                 trainers=self._serve_attrs.get("trainers", 1))
            ps.serve_forever()
            return []
        program = self.program
        if program._version != self._program_version:
            raise RuntimeError(
                "program was mutated after prepare(); prepare() it again "
                "(Executor.run() re-prepares automatically)")
        # telemetry gate: ONE flag read + branch when off — the prepared
        # fast path performs zero registry writes unless observing
        obs_on = _flags.get_flag("observe")
        t0 = time.perf_counter() if obs_on else 0.0
        feed = feed or {}
        # py_reader-fed program: no feed -> pop the next queued batch
        # (raises EOFException at end of pass, reference read-op contract)
        if not feed and getattr(program, "_py_reader", None) is not None:
            feed = program._py_reader.next_feed()
        # steady state: the feed-conversion PLAN (per-name target dtype,
        # no LoD) was resolved at bind time, so conversion is one tight
        # loop without block-var lookups or dtype re-resolution
        plan = self._feed_plan
        if plan is not None and feed.keys() == self._plan_keys:
            feed_arrays = {}
            for name, val in feed.items():
                if type(val) is np.ndarray:
                    dt = plan[name]
                    if dt is not None and val.dtype != dt \
                            and val.dtype.kind in "fiub":
                        val = val.astype(dt)
                    feed_arrays[name] = val
                elif isinstance(val, jax.Array):
                    feed_arrays[name] = val   # pre-placed: never round-trip
                else:
                    arr = np.asarray(val)
                    dt = plan[name]
                    if dt is not None and arr.dtype != dt \
                            and arr.dtype.kind in "fiub":
                        arr = arr.astype(dt)
                    feed_arrays[name] = arr
        else:
            feed_arrays = _convert_feed_dict(self._block, feed)
        if obs_on:
            t_fc = time.perf_counter()  # end of feed conversion proper
        entry = self._entry
        bound = False
        if entry is None or feed_arrays.keys() != self._entry_keys:
            entry = self._bind(feed, feed_arrays)
            bound = True
        if obs_on:
            # feed_shape observatory: a new shape/dtype signature on a
            # bound entry means jax.jit retraces + XLA recompiles
            _steplog.track_shapes(entry, program._uid, feed_arrays,
                                  source=self.telemetry_source)
            t1 = time.perf_counter()
        counter = self._exe._count_run(program._uid)
        mut, const = self._state.get(entry, self.scope)
        if obs_on:
            t2 = time.perf_counter()
        if self._use_device_ctx:
            with jax.default_device(self._device):
                fetches, new_state = entry.run_with_state(
                    self.scope, feed_arrays, mut, const, counter)
        else:
            fetches, new_state = entry.run_with_state(
                self.scope, feed_arrays, mut, const, counter)
        if obs_on:
            t3 = time.perf_counter()
        self._state.commit(entry, self.scope, new_state)
        if obs_on:
            t4 = time.perf_counter()
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        if obs_on:
            t5 = time.perf_counter()
            # device_compute is the run_with_state wall: jitted dispatch +
            # (under sync dispatch) device time + the in-call scope update
            # (first call also traces + XLA-compiles inside it);
            # write_back is the state-cache commit; fetch is the host
            # transfer np.asarray forces (zero when return_numpy=False —
            # the async-dispatch overlap the fast path is built on).
            # Binding (validation, feed plan, cache lookup) is recorded as
            # its own one-shot `bind` phase so it never pollutes the
            # steady-state feed_convert numbers.
            phases = {
                "feed_convert": t_fc - t0,
                "state_gather": t2 - t1,
                "device_compute": t3 - t2,
                "write_back": t4 - t3,
                "fetch": t5 - t4,
            }
            if bound:
                phases["bind"] = t1 - t_fc
            _steplog.get_steplog().record(_steplog.StepStats(
                program._uid, self.telemetry_source, time.time(), phases))
        return fetches

    def _build_feed_plan(self, feed):
        """Per-name target dtype for the bound feed set, resolved once.
        LoD feeds ((data, lengths) tuples) keep the generic conversion —
        they expand into @SEQLEN companions the plan doesn't model."""
        plan = {}
        for name, val in feed.items():
            var = self._block.vars.get(name)
            if var is not None and var.lod_level > 0:
                # a LoD var may be fed as a plain array on one step and a
                # (data, lengths) tuple on another — only the generic
                # conversion models that
                return None
            plan[name] = (jnp.dtype(var.dtype)
                          if var is not None and var.dtype else None)
        return plan

    def _bind(self, feed, feed_arrays) -> _CompiledProgram:
        """Resolve the compiled entry for this feed signature, consulting
        the executor-wide compile cache so re-preparing (e.g. after an
        unrelated flag flip) never recompiles an unchanged step."""
        sig = tuple(sorted(feed_arrays))
        entry = self._entries.get(sig)
        if entry is None:
            exe, program = self._exe, self.program
            copts = self._copts
            cache_key = (program._uid, program._version, sig,
                         tuple(self.fetch_names), self.scope._uid, exe.amp,
                         self._check_nan_inf, self._dropout_impl,
                         tuple(sorted(copts.items())) if copts else None,
                         program.random_seed)  # seed is baked into the trace
            entry = exe._cache.get(cache_key)
            if entry is None:
                # recompilation observatory: a compile-cache miss means a
                # new XLA executable — record it with its attributed cause
                # (first_call / program_version / copts_change / ...)
                _steplog.observatory().note_entry_build(
                    program._uid, program._version, sig,
                    tuple(self.fetch_names),
                    tuple(sorted(copts.items())) if copts else None,
                    source=self.telemetry_source, scope_uid=self.scope._uid)
                if _flags.get_flag("observe"):
                    # fluid-pulse memory observatory: a compile costs
                    # seconds, the concrete-shape walk costs milliseconds
                    # — estimate this program's peak HBM at the shapes it
                    # is about to compile for (never raises)
                    from ..observe import memory as _obs_memory
                    _obs_memory.note_program(
                        program, feed_arrays, source=self.telemetry_source)
                stream = exe._stream_for(program._uid)
                with jax.default_device(self._device):
                    entry = _CompiledProgram(
                        program, sig, self.fetch_names, self.scope,
                        donate=True, amp=exe.amp,
                        check_nan_inf=self._check_nan_inf,
                        compiler_options=copts, rng_stream=stream)
                _evict_stale_versions(exe._cache, program._uid,
                                      program._version)
                exe._cache[cache_key] = entry
            self._entries[sig] = entry
        self._entry = entry
        self._entry_keys = frozenset(sig)
        # the ctx can be skipped only when this handle's device IS the
        # process default: jit outputs are UNCOMMITTED, so a stateful step
        # with numpy feeds would otherwise migrate to jax's global default
        # backend (e.g. CPUPlace selected in a TPU-default process) —
        # place selection must hold even without the per-step ctx
        try:
            default_dev = (jax.config.jax_default_device
                           or jax.local_devices()[0])
        except Exception:
            default_dev = None
        self._use_device_ctx = (self._device != default_dev
                                or not (entry.mut_names or entry.const_names))
        self._feed_plan = self._build_feed_plan(feed)
        self._plan_keys = frozenset(feed)
        return entry


class Executor:
    """Program runner (reference executor.py:224).

    `place` selects the device; `exe.run(program, feed=..., fetch_list=...)`
    matches the reference API. Programs are compiled on first run and
    cached. `run()` itself rides a memoized `PreparedProgram` (the
    reference's Prepare/RunPreparedContext split), so steady-state steps
    skip the per-step cache-key rebuild, flag reads, and full scope state
    gather; loops that want the last few µs hold a `prepare()` handle
    directly.
    """

    def __init__(self, place: Optional[Place] = None, amp: bool = False,
                 check_nan_inf: Optional[bool] = None):
        self.place = place or TPUPlace(0)
        self.amp = amp  # bf16 mixed precision (reference float16_transpiler analog)
        # debug mode: per-op finite checks (reference FLAGS_check_nan_inf).
        # None = follow the flag registry at run time, so
        # set_flag("check_nan_inf", True) takes effect on the next run
        # (a new cache entry compiles with the checks baked in).
        self._check_nan_inf = check_nan_inf
        self._cache: Dict[tuple, _CompiledProgram] = {}
        self._prepared: Dict[tuple, PreparedProgram] = {}
        self._run_counts: Dict[int, int] = {}  # program uid -> runs so far
        self._prog_order: Dict[int, int] = {}  # program uid -> ordinal
        self._next_stream = 0  # monotone ordinal source (survives eviction)

    @property
    def check_nan_inf(self) -> bool:
        if self._check_nan_inf is None:
            return _flags.get_flag("check_nan_inf")
        return self._check_nan_inf

    @check_nan_inf.setter
    def check_nan_inf(self, value):
        self._check_nan_inf = value

    def _stream_for(self, uid: int) -> int:
        """Executor-local program ordinal for unseeded rng streams. A
        monotone counter (not len()) so the leak-backstop eviction can
        never recycle a live ordinal onto a second program."""
        po = self._prog_order
        s = po.get(uid)
        if s is None:
            if len(po) >= _MAX_TRACKED_PROGRAMS:
                po.pop(next(iter(po)))
            s = self._next_stream
            self._next_stream += 1
            po[uid] = s
        return s

    def _count_run(self, uid: int) -> np.uint32:
        """PER-PROGRAM run counter: the PRNG key is fold_in(key(seed),
        runs-of-THIS-program), so a seeded startup re-initializes
        identically no matter what else this executor ran (cross-
        executor/mesh parity), while seeded TRAINING still draws a
        fresh-but-reproducible mask every step (reference random_seed
        reproducibility with per-step variation — the round-3 dropout
        contract, tests/test_amp_perf_ops.py)."""
        rc = self._run_counts
        n = rc.get(uid)
        if n is None:
            n = 0
            if len(rc) >= _MAX_TRACKED_PROGRAMS:
                rc.pop(next(iter(rc)))
        rc[uid] = n + 1
        return np.uint32(n)

    def prepare(self,
                program: Optional[ir.Program] = None,
                feed_names: Optional[Sequence[str]] = None,
                fetch_list: Optional[Sequence[Union[str, ir.Variable]]] = None,
                scope: Optional[Scope] = None,
                validate: Optional[str] = None) -> PreparedProgram:
        """Resolve the per-step-invariant work ONCE and return a bound
        `PreparedProgram` whose `run(feed)` is the fast path (reference
        Executor::Prepare + RunPreparedContext, executor.cc:294-366).
        `feed_names` is advisory (the real feed signature, including LoD
        @SEQLEN companions, binds on the first run's actual values).
        `validate="error"|"warn"|"off"` runs the static verifier
        (analysis/) over the program before anything lowers; None follows
        the `validate` flag (default off)."""
        program = program or ir.default_main_program()
        scope = scope or global_scope()
        return PreparedProgram(self, program, fetch_list, scope,
                               feed_names=feed_names, validate=validate)

    def run(self,
            program: Optional[ir.Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[Union[str, ir.Variable]]] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        program = program or ir.default_main_program()
        scope = scope or global_scope()
        if not use_program_cache:
            return self._run_uncached(program, feed, fetch_list, scope,
                                      return_numpy)
        # Thin wrapper over a memoized PreparedProgram: existing callers
        # get the prepared fast path for free. The memo key is everything
        # a handle bakes in — program identity+version (covers random_seed
        # mutation), fetch set, scope, executor settings, and the flag
        # registry version (one int compare standing in for the per-step
        # flag reads the old path did).
        fetch_names = tuple(f.name if isinstance(f, ir.Variable) else str(f)
                            for f in (fetch_list or ()))
        key = (program._uid, program._version, fetch_names, scope._uid,
               self.amp, self._check_nan_inf, _flags.version())
        prepared = self._prepared.get(key)
        if prepared is None:
            prepared = PreparedProgram(self, program, fetch_names, scope)
            _evict_stale_versions(self._prepared, program._uid,
                                  program._version)
            # a flag flip (or check_nan_inf toggle) re-keys the memo for
            # the SAME (program, fetch set, scope) — drop the superseded
            # handle (the compiled entries live in self._cache and reuse)
            _evict_superseded(self._prepared, key)
            # hard cap (FIFO): a handle pins its scope AND the gathered
            # state arrays, so per-call temporary scopes (exe.run(prog,
            # scope=Scope()) in a serving loop) would otherwise keep one
            # full parameter set alive per call. Evicted handles rebuild
            # cheaply — the compiled entries stay in self._cache.
            if len(self._prepared) >= _MAX_PREPARED_HANDLES:
                self._prepared.pop(next(iter(self._prepared)))
            self._prepared[key] = prepared
        return prepared.run(feed, return_numpy=return_numpy)

    def _run_uncached(self, program, feed, fetch_list, scope, return_numpy):
        """use_program_cache=False: compile fresh, bypass both caches
        (reference semantics; used by tests probing recompilation)."""
        fetch_names = [f.name if isinstance(f, ir.Variable) else str(f)
                       for f in (fetch_list or [])]
        block = program.global_block()
        ls = [op for op in block.ops if op.type == "listen_and_serv"]
        if ls:
            from ..pserver.server import ParameterServer
            ps = ParameterServer(ls[0].attrs["endpoint"],
                                 trainers=ls[0].attrs.get("trainers", 1))
            ps.serve_forever()
            return []
        feed = feed or {}
        if not feed and getattr(program, "_py_reader", None) is not None:
            feed = program._py_reader.next_feed()
        feed_arrays = _convert_feed_dict(block, feed)
        copts = resolve_compiler_options(self.place.jax_device().platform,
                                         program)
        # deliberate cache bypass: recorded as its own cause, without
        # polluting the observatory's attribution state for cached runs
        _steplog.observatory().record(program._uid, "uncached", "executor")
        stream = self._stream_for(program._uid)
        with jax.default_device(self.place.jax_device()):
            compiled = _CompiledProgram(program, sorted(feed_arrays),
                                        fetch_names, scope, donate=True,
                                        amp=self.amp,
                                        check_nan_inf=self.check_nan_inf,
                                        compiler_options=copts,
                                        rng_stream=stream)
        counter = self._count_run(program._uid)
        with jax.default_device(self.place.jax_device()):
            fetches = compiled.run(scope, feed_arrays, counter)
        if return_numpy:
            fetches = [np.asarray(f) for f in fetches]
        return fetches

    def close(self):
        self._cache.clear()
        self._prepared.clear()


import contextlib as _contextlib


def _switch_scope(scope: Scope) -> Scope:
    """Swap the process-global scope, returning the previous one
    (reference executor.py _switch_scope)."""
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    return prev


@_contextlib.contextmanager
def scope_guard(scope: Scope):
    """Run a `with` region against `scope` as the global scope (reference
    executor.py scope_guard)."""
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)


def fetch_var(name: str, scope: Optional[Scope] = None, return_numpy: bool = True):
    """Read a variable's current value from a scope (reference
    executor.py fetch_var)."""
    scope = scope or global_scope()
    val = scope.find_var(name)
    if val is None:
        raise KeyError(f"fetch_var: variable {name!r} not found in scope")
    return np.asarray(val) if return_numpy else val
