"""Block -> XLA lowering.

This replaces the reference's executor hot loop (`for op in ops: op->Run(...)`,
reference: paddle/fluid/framework/executor.cc:321-366) and its per-op kernel
dispatch (operator.cc:635). TPU-native redesign: the whole block is traced
once through each op's JAX lowering rule into ONE jit-compiled XLA
computation; XLA then fuses/schedules what the reference interpreted op by op.

Gradient ops (produced by core/backward.py) are lowered generically: the
forward rule is re-traced under `jax.vjp`. Duplicate forward subexpressions
are eliminated by XLA CSE inside the single jit, so no residual plumbing is
required in the IR.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from . import ir, registry, types
from .ir import SEQLEN_SUFFIX
from .registry import EMPTY_VAR, FWD_OP_ATTR, GRAD_OP_SUFFIX, LoweringContext


class BlockLowerer:
    """Lowers a Block's op list into a pure function over an env dict."""

    def __init__(self, program: ir.Program, amp: bool = False,
                 check_nan_inf: bool = False, mesh=None):
        self.program = program
        # bf16 mixed precision for MXU ops (registry.AMP_OPS); params stay
        # fp32, accumulation is fp32 on the MXU.
        self.amp = amp
        # device mesh when compiling under ParallelExecutor; ops with
        # mesh-aware lowerings (fused_attention -> ring attention over the
        # 'sp' axis) read it via ctx.lowerer.mesh
        self.mesh = mesh
        # reference FLAGS_check_nan_inf (CheckTensorNANOrInf after every op,
        # operator.cc:622-634). XLA programs cannot raise, so each op's
        # float outputs contribute an all-finite flag; the executor checks
        # the flags on the host after the step and raises naming the first
        # offending (op, var).
        self.check_nan_inf = check_nan_inf
        self.nan_flags: List[tuple] = []  # (op_type, var_name, flag) per trace
        # control-flow sub-blocks lower inside lax.scan/while/cond body
        # traces where a recorded flag would be a leaked tracer; interior
        # ops are therefore covered at the control-flow op's boundary
        # (its outputs are checked at depth 1)
        self._block_depth = 0

    def run_block(self, block_idx: int, env: Dict[str, Any], key) -> Dict[str, Any]:
        """Execute all ops of `block_idx` on `env` (name -> jnp array),
        mutating and returning it. `key` is the step's base PRNG key."""
        block = self.program.blocks[block_idx]
        self._block_depth += 1
        try:
            for op_idx, op in enumerate(block.ops):
                self._run_op(block, op, op_idx, env, key)
        finally:
            self._block_depth -= 1
        return env

    # -- single op -------------------------------------------------------
    def _run_op(self, block: ir.Block, op: ir.Operator, op_idx: int,
                env: Dict[str, Any], key):
        if op.type.endswith(GRAD_OP_SUFFIX) and FWD_OP_ATTR in op.attrs:
            self._run_grad_op(block, op, env, key)
            if self.check_nan_inf and self._block_depth == 1:
                self._record_nan_flags_env(op, env)
            return
        opdef = registry.get_op_def(op.type)
        op_key = jax.random.fold_in(key, _op_seed(op, op_idx)) if opdef.needs_rng else None
        ins = _gather_inputs(op.inputs, env, op.type)
        ctx = LoweringContext(op.attrs, key=op_key, lowerer=self, op=op, env=env)
        outs = registry.call_rule(opdef, ctx, ins)
        _scatter_outputs(op, outs, env)
        if opdef.propagate_seqlen:
            _propagate_seqlen(op, env)
        if self.check_nan_inf and self._block_depth == 1:
            self._record_nan_flags(op, outs)

    def _record_nan_flags(self, op, outs):
        for slot, names in op.outputs.items():
            for name, val in zip(names, outs.get(slot, [])):
                self._record_one_flag(op.type, name, val)

    def _record_nan_flags_env(self, op, env):
        # grad ops scatter their outputs straight into env (vjp path);
        # check whatever actually got written
        for name in op.output_arg_names:
            self._record_one_flag(op.type, name, env.get(name))

    def _record_one_flag(self, op_type, name, val):
        if val is None or not hasattr(val, "dtype"):
            return
        if jnp.issubdtype(jnp.asarray(val).dtype, jnp.floating):
            self.nan_flags.append(
                (op_type, name, jnp.all(jnp.isfinite(val))))

    # -- generic vjp-based grad op --------------------------------------
    def _run_grad_op(self, block: ir.Block, op: ir.Operator,
                     env: Dict[str, Any], key):
        fwd = op.attrs[FWD_OP_ATTR]          # forward OpDesc as dict
        fwd_type, fwd_inputs, fwd_outputs = fwd["type"], fwd["inputs"], fwd["outputs"]
        fwd_attrs, fwd_idx = fwd["attrs"], fwd.get("__idx__", 0)
        opdef = registry.get_op_def(fwd_type)
        op_key = jax.random.fold_in(key, fwd_idx) if opdef.needs_rng else None

        if opdef.grad_lower is not None:
            ins = {s: [env[n] for n in ns] for s, ns in fwd_inputs.items()}
            out_grads = {}
            for slot, names in fwd_outputs.items():
                out_grads[slot] = [env.get(ir.grad_var_name(n)) for n in names]
            # forward OUTPUT values (already materialized in env): grads
            # that consume a saved output (reference convention, e.g.
            # softmax_grad takes Out) read them from ctx.fwd_outs instead
            # of recomputing
            fwd_outs = {slot: [env.get(n) for n in names]
                        for slot, names in fwd_outputs.items()}
            ctx = LoweringContext(fwd_attrs, key=op_key, lowerer=self, op=op)
            ctx.fwd_outs = fwd_outs
            grads = opdef.grad_lower(ctx, ins, out_grads)
            _write_input_grads(op, fwd_inputs, grads, env)
            return

        # Flatten differentiable fwd inputs; keep the rest closed over.
        diff_entries: List[tuple] = []   # (slot, pos, name)
        for slot, names in fwd_inputs.items():
            for pos, name in enumerate(names):
                val = env[name]
                if jnp.issubdtype(jnp.asarray(val).dtype, jnp.floating):
                    diff_entries.append((slot, pos, name))
        wanted = _wanted_input_grads(op)
        diff_entries = [e for e in diff_entries if e[2] in wanted]
        if not diff_entries:
            return
        diff_vals = [env[name] for _, _, name in diff_entries]
        if fwd_attrs.get("__remat__"):
            # memory_optimize marked this op: barrier the recompute inputs so
            # XLA cannot CSE the backward's re-traced forward with the
            # original — the activation is rematerialized, not kept in HBM
            diff_vals = list(jax.lax.optimization_barrier(tuple(diff_vals)))

        out_slots = [(slot, names) for slot, names in fwd_outputs.items() if names]

        def fwd_fn(*vals):
            ins = {s: [env[n] for n in ns] for s, ns in fwd_inputs.items()}
            # control-flow rules read values through ctx.env, not slot args —
            # patch a shadow env so perturbations flow through jax.vjp
            env2 = dict(env)
            for (slot, pos, name), v in zip(diff_entries, vals):
                ins[slot][pos] = v
                env2[name] = v
            ctx = LoweringContext(fwd_attrs, key=op_key, lowerer=self, env=env2)
            outs = registry.call_rule(opdef, ctx, ins)
            flat = []
            for slot, names in out_slots:
                flat.extend(outs[slot][: len(names)])
            return tuple(flat)

        declared_by_base = _declared_by_base(op)
        primals, vjp_fn = jax.vjp(fwd_fn, *diff_vals)
        cotangents = []
        i = 0
        for slot, names in out_slots:
            for name in names:
                primal = primals[i]
                i += 1
                g = env.get(ir.grad_var_name(name))
                if g is None:
                    g = _zero_cotangent(primal)
                elif jnp.issubdtype(jnp.asarray(primal).dtype, jnp.floating):
                    g = jnp.asarray(g, jnp.asarray(primal).dtype)
                else:
                    g = _zero_cotangent(primal)
                cotangents.append(g)
        in_grads = vjp_fn(tuple(cotangents))

        # Accumulate per-variable (a var may appear in several input slots).
        acc: Dict[str, Any] = {}
        for (slot, pos, name), g in zip(diff_entries, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                continue
            acc[name] = g if name not in acc else acc[name] + g
        for name, g in acc.items():
            if name in declared_by_base:
                env[declared_by_base[name]] = g


def _op_seed(op: ir.Operator, op_idx: int) -> int:
    return int(op.attrs.get("__idx__", op_idx))


def _gather_inputs(inputs: Dict[str, List[str]], env: Dict[str, Any], op_type: str):
    ins = {}
    for slot, names in inputs.items():
        vals = []
        for n in names:
            if n == EMPTY_VAR:
                vals.append(None)
                continue
            if n not in env:
                raise KeyError(f"op {op_type}: input var {n!r} not materialized")
            vals.append(env[n])
        ins[slot] = vals
    return ins


def _scatter_outputs(op: ir.Operator, outs: Dict[str, List[Any]], env: Dict[str, Any]):
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = outs[slot]
        if len(vals) < len(names):
            raise ValueError(f"op {op.type}: slot {slot} produced {len(vals)} values "
                             f"for {len(names)} outputs")
        for name, val in zip(names, vals):
            if name != EMPTY_VAR and val is not None:
                env[name] = val


def _propagate_seqlen(op: ir.Operator, env: Dict[str, Any]):
    """Variable-length (LoD-analog) bookkeeping: elementwise-ish ops carry
    the first input's length companions onto their outputs — the bare
    @SEQLEN (outer level) and, for nested LoD, the @SEQLEN.1 inner
    lengths."""
    for suffix in (SEQLEN_SUFFIX, SEQLEN_SUFFIX + ".1"):
        src = None
        for names in op.inputs.values():
            for n in names:
                if n != EMPTY_VAR and (n + suffix) in env:
                    src = env[n + suffix]
                    break
            if src is not None:
                break
        if src is None:
            continue
        for names in op.outputs.values():
            for n in names:
                if n != EMPTY_VAR and n in env and (n + suffix) not in env:
                    val = env[n]
                    if hasattr(val, "ndim") and val.ndim >= 2 \
                            and val.shape[0] == src.shape[0]:
                        env[n + suffix] = src


def _grad_base(grad_name: str) -> str:
    """`x@GRAD` or `x@GRAD@RENAME@k` -> `x` (fan-in contributions are renamed
    by core/backward.py before a `sum` op re-merges them)."""
    return grad_name.split(ir.GRAD_SUFFIX)[0]


def _declared_by_base(grad_op: ir.Operator) -> Dict[str, str]:
    out = {}
    for names in grad_op.outputs.values():
        for n in names:
            if n != EMPTY_VAR and ir.GRAD_SUFFIX in n:
                out[_grad_base(n)] = n
    return out


def _wanted_input_grads(grad_op: ir.Operator) -> Set[str]:
    return set(_declared_by_base(grad_op))


def _write_input_grads(grad_op, fwd_inputs, grads: Dict[str, Any], env):
    declared = _declared_by_base(grad_op)
    for slot, g in grads.items():
        names = fwd_inputs.get(slot, [])
        gs = g if isinstance(g, (list, tuple)) else [g]
        for name, gv in zip(names, gs):
            if gv is None or name not in declared:
                continue
            gname = declared[name]
            env[gname] = gv if gname not in env else env[gname] + gv


def _zero_cotangent(primal):
    arr = jnp.asarray(primal)
    if jnp.issubdtype(arr.dtype, jnp.floating):
        return jnp.zeros(arr.shape, arr.dtype)
    return np.zeros(arr.shape, jax.dtypes.float0)
