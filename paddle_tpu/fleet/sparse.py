"""fluid-fleet: serve-time distributed embedding lookup.

The DeepFM-class serving problem: the embedding table is the model —
and at recsys scale it does not fit one serving host. Training already
solved this shape with the pserver sparse tables (row-sharded by
``id % n_servers``, prefetch + sparse push); this module is the READ
half of that path relocated to inference time, so a model whose tables
live only in pserver shards serves end to end:

- ``save_sparse_inference_model`` saves an inference dir WITHOUT the
  distributed tables' values (``io.save_inference_model(exclude_vars=)``)
  and records their specs under the manifest's ``sparse`` key;
- ``SparseServeConfig`` is what a replica passes to ``add_model`` — it
  owns one READ-ONLY ``PSClient`` (``read_only=True``: a serving process
  physically cannot push) with the fluid-wire codec negotiated, so
  embedding-row pulls ride a wire ~4x cheaper than raw;
- ``SparseLookupPlan`` (one per ModelVersion) augments each coalesced
  batch: unique the batch's ids, pull missing rows through a bounded
  LRU ``RowCache``, feed a fixed-shape ``[cap, width]`` sub-table under
  the table's own name with ids remapped — the exact feed idiom
  ``AsyncPSTrainer`` uses for training, so the program needs no rewrite
  and the compile signature is constant (zero steady-state recompiles).

Freshness contract: cached rows are as fresh as the last pull; the
cache is keyed to its ModelVersion and dropped when the version retires,
so a hot swap IS the invalidation point — a model push that retrains
embeddings swaps the dir and every replica re-pulls. ``invalidate()``
exists for out-of-band refreshes.

fluid-haven: with the pserver shards running as replicated pairs, pass
``SparseServeConfig(endpoints=[primary], replicas={primary: [backup]})``
— a standby backup serves bounded-stale row reads WITHOUT promotion, so
a primary SIGKILL never takes the serving plane down with it (the read
fails over per-request; after a handover the retired primary's redirect
moves the client to the successor). Pinned by
``tests/test_haven.py::test_fleet_sparse_row_pulls_survive_primary_kill``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observe import metrics as _metrics
from ..serve.errors import BadRequestError

#: MANIFEST.json key carrying the pserver-resident table specs
SPARSE_MANIFEST_KEY = "sparse"

#: default bound on cached rows per plan (per version) — at DeepFM width
#: 16 f32 this is ~4 MB; sized for the hot head of a zipfian id stream
DEFAULT_CACHE_ROWS = 65536


def sparse_table_specs(program) -> Dict[str, dict]:
    """{table name: spec} for every ``is_distributed`` lookup_table op in
    `program` — the serve-side twin of the transpiler's sparse_specs
    scan (distribute_transpiler._build_async_plan step 1)."""
    specs: Dict[str, dict] = {}
    block = program.global_block()
    for op in block.ops:
        if op.type != "lookup_table" or not op.attrs.get("is_distributed"):
            continue
        wname = op.input("W")[0]
        w = block._find_var_recursive(wname)
        spec = specs.setdefault(wname, {
            "rows": int(w.shape[0]), "width": int(w.shape[1]),
            "dtype": str(w.dtype), "ids_names": [],
        })
        ids_name = op.input("Ids")[0]
        if ids_name not in spec["ids_names"]:
            spec["ids_names"].append(ids_name)
    return specs


def save_sparse_inference_model(dirname, feeded_var_names, target_vars,
                                executor, main_program=None, scope=None,
                                cap: int = 256, manifest_extra=None,
                                **save_kwargs):
    """``io.save_inference_model`` for a model with distributed lookup
    tables: the tables' VALUES stay out of the dir (they live in pserver
    shards), and the manifest's ``sparse`` key records what a serving
    replica must prefetch — table specs, the ids feeds each table reads,
    and ``cap`` (the max unique rows one padded batch may touch; the
    fed sub-table's fixed row count).

    Raises BadRequestError when the program has no distributed table —
    use the plain save in that case (a silently-empty sparse key would
    make every replica demand pserver endpoints for nothing)."""
    from .. import io as _io
    from ..core import executor as core_exec

    main_program = main_program or _io.ir.default_main_program()
    pruned = _io.get_inference_program(target_vars, main_program)
    specs = sparse_table_specs(pruned)
    if not specs:
        raise BadRequestError(
            "save_sparse_inference_model: no is_distributed lookup_table "
            "in the pruned inference program — use io.save_inference_model")
    # the ids every table reads must be FED (the plan remaps them on the
    # host); a lookup over a computed ids tensor can't ride this path
    feed_set = set(feeded_var_names)
    for wname, spec in specs.items():
        missing = [n for n in spec["ids_names"] if n not in feed_set]
        if missing:
            raise BadRequestError(
                f"distributed table {wname!r} is looked up with ids "
                f"{missing} that are not model feeds — the serve-time "
                f"remap happens on the host feed boundary")
    # exclude the tables AND their table-SIZED derived state: a trained
    # program's pruned slice still carries persistable optimizer slots
    # (fm_v_moment_0, [rows, width]) — saving those would smuggle the
    # too-big-for-one-host bytes right back into the model dir. The full
    # skip list is RECORDED in the manifest so the loader skips exactly
    # what the saver excluded (no naming-rule drift between the two).
    exclude = set(specs)
    for v in pruned.global_block().vars.values():
        if v.persistable and any(v.name.startswith(w + "_")
                                 for w in specs):
            exclude.add(v.name)
    extra = {SPARSE_MANIFEST_KEY: {"cap": int(cap), "tables": specs,
                                   "skip_vars": sorted(exclude)},
             **(manifest_extra or {})}
    scope = scope or core_exec.global_scope()
    return _io.save_inference_model(
        dirname, feeded_var_names, target_vars, executor,
        main_program=main_program, scope=scope,
        exclude_vars=exclude, manifest_extra=extra, **save_kwargs)


class RowCache:
    """Bounded LRU of (table, id) -> row. Thread-safe; rows are stored
    as copies so a cached row can never alias a caller's buffer."""

    def __init__(self, capacity_rows: int = DEFAULT_CACHE_ROWS):
        self.capacity = int(capacity_rows)
        self._lock = threading.Lock()
        self._rows: OrderedDict = OrderedDict()

    def __len__(self):
        with self._lock:
            return len(self._rows)

    def get(self, table: str, row_id: int) -> Optional[np.ndarray]:
        key = (table, row_id)
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                self._rows.move_to_end(key)
            return row

    def put(self, table: str, row_id: int, row: np.ndarray) -> None:
        key = (table, row_id)
        with self._lock:
            self._rows[key] = np.array(row, copy=True)
            self._rows.move_to_end(key)
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()


class SparseServeConfig:
    """What a replica passes to ``add_model(..., sparse=)``: where the
    rows live and how to pull them. Owns ONE read-only PSClient shared
    by every version/model it builds plans for (sockets and wire-codec
    negotiation survive hot swaps; only the row CACHE is per-version)."""

    def __init__(self, endpoints: Sequence[str],
                 comm_quant: Optional[str] = None,
                 cache_rows: int = DEFAULT_CACHE_ROWS,
                 replicas: Optional[Dict[str, Sequence[str]]] = None,
                 retry=None, deadline: Optional[float] = 10.0,
                 client=None):
        from ..pserver.client import PSClient

        self.endpoints = list(endpoints)
        self.cache_rows = int(cache_rows)
        self._own_client = client is None
        self.client = client if client is not None else PSClient(
            self.endpoints, comm_quant=comm_quant, replicas=replicas,
            retry=retry, deadline=deadline, read_only=True)

    def build(self, sparse_meta: dict, ver) -> "SparseLookupPlan":
        """ModelRegistry hook: one plan (and one row cache) per loaded
        ModelVersion."""
        return SparseLookupPlan(self.client, sparse_meta,
                                model=ver.name,
                                version_key=ver.version_key,
                                cache_rows=self.cache_rows)

    def close(self):
        if self._own_client:
            self.client.close()


class SparseLookupPlan:
    """The per-version read path: augment a padded batch's feeds with
    prefetched sub-tables. Tables sharing an ids feed share one
    uniq/remap (a fed ids var holds exactly one mapping) — the same
    grouping rule as the training-side AsyncPSTrainer."""

    def __init__(self, client, sparse_meta: dict, model: str,
                 version_key: str, cache_rows: int = DEFAULT_CACHE_ROWS):
        from ..pserver.trainer import AsyncPSTrainer

        self.client = client
        self.model = model
        self.version_key = version_key
        self.cap = int(sparse_meta["cap"])
        self.tables: Dict[str, dict] = dict(sparse_meta["tables"])
        self.groups: List[dict] = AsyncPSTrainer._group_tables(self.tables)
        self.cache = RowCache(cache_rows)
        self.hits = 0          # plan-local tallies for stats()/tests
        self.misses = 0
        self._m_hits = _metrics.counter(
            "fleet_sparse_cache_hits_total",
            "serve-time sparse lookups answered from the row cache")
        self._m_miss = _metrics.counter(
            "fleet_sparse_cache_misses_total",
            "serve-time sparse lookups pulled from pserver shards")
        self._m_rows = _metrics.gauge(
            "fleet_sparse_cache_rows", "rows held in the serve row cache")

    # -- warmup (no RPC) ---------------------------------------------------

    def warm_feeds(self, feeds: Dict[str, np.ndarray]
                   ) -> Dict[str, np.ndarray]:
        """The warm-compile twin of augment(): identical feed NAMES and
        SHAPES (zero sub-tables, untouched ids) so warmed signatures
        cover steady-state traffic — and not a single pserver RPC at
        load time."""
        feeds = dict(feeds)
        for wname, spec in self.tables.items():
            feeds[wname] = np.zeros((self.cap, spec["width"]),
                                    dtype=spec["dtype"])
        return feeds

    # -- the request path --------------------------------------------------

    def augment(self, feeds: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        """Resolve one padded batch: per table group, unique the ids,
        pull rows (cache first), feed the [cap, width] sub-table under
        the table's name and the remapped ids under the ids feeds'
        names. Runs on the model's executor thread — the cache is what
        keeps the hot-id common case RPC-free."""
        feeds = dict(feeds)
        for g in self.groups:
            ids_vals = [np.asarray(feeds[n]) for n in g["ids_names"]]
            flat = np.concatenate([v.reshape(-1) for v in ids_vals])
            uniq, inv = np.unique(flat, return_inverse=True)
            m = int(uniq.shape[0])
            if m > self.cap:
                raise BadRequestError(
                    f"model {self.model!r}: batch touches {m} unique rows "
                    f"of {g['tables']} but the manifest's sparse cap is "
                    f"{self.cap} — lower the rows ladder or re-save with "
                    f"a larger cap")
            for wname in g["tables"]:
                spec = self.tables[wname]
                sub = np.zeros((self.cap, spec["width"]),
                               dtype=spec["dtype"])
                if m:
                    sub[:m] = self._rows_for(wname, uniq)
                feeds[wname] = sub
            off = 0
            for n, v in zip(g["ids_names"], ids_vals):
                feeds[n] = inv[off:off + v.size].reshape(v.shape) \
                    .astype(v.dtype)
                off += v.size
        return feeds

    def _rows_for(self, wname: str, uniq: np.ndarray) -> np.ndarray:
        spec = self.tables[wname]
        rows = np.empty((uniq.shape[0], spec["width"]),
                        dtype=spec["dtype"])
        missing: List[int] = []
        for j, rid in enumerate(uniq.tolist()):
            cached = self.cache.get(wname, rid)
            if cached is None:
                missing.append(j)
            else:
                rows[j] = cached
        hits = uniq.shape[0] - len(missing)
        if hits:
            self.hits += hits
            self._m_hits.inc(hits, model=self.model, table=wname)
        if missing:
            self.misses += len(missing)
            self._m_miss.inc(len(missing), model=self.model, table=wname)
            miss_ids = uniq[missing]
            pulled = self.client.prefetch_rows(wname, miss_ids)
            rows[missing] = pulled
            for j, rid in zip(missing, miss_ids.tolist()):
                self.cache.put(wname, rid, rows[j])
            self._m_rows.set(len(self.cache), model=self.model)
        return rows

    # -- lifecycle ---------------------------------------------------------

    def invalidate(self) -> None:
        """Drop every cached row (out-of-band refresh; the normal
        invalidation is the version swap retiring this whole plan)."""
        self.cache.clear()
        self._m_rows.set(0, model=self.model)

    def close(self) -> None:
        self.invalidate()

    def stats(self) -> dict:
        return {
            "cap": self.cap,
            "tables": sorted(self.tables),
            "cached_rows": len(self.cache),
            "cache_hits": self.hits,
            "cache_misses": self.misses,
        }
