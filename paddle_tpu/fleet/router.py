"""fluid-fleet router: health-gated membership + least-loaded dispatch +
coordinated hot swap over N replica InferenceServers.

The TF system paper's serving story at fleet scale, built from parts
this repo already trusts:

- **Membership** is ark heartbeat leases (`ark.LeaseTable`): replicas
  renew at a third of the lease; a SIGKILLed replica stops renewing and
  drops out of dispatch within lease-time. A successful readiness poll
  ALSO renews the lease (probe evidence of liveness), so statically
  added replicas (tests, loadgen) need no replica-side heartbeat loop.
- **Readiness** is the fluid-pulse `/readyz` contract: a poll thread
  GETs each replica's pulse endpoint (HTTP) when one is advertised,
  falling back to the replica's `readyz` RPC (identical body). A
  replica takes traffic only when its verdict is ok AND the model's
  active version is WARMED and matches the fleet's committed version —
  "right version, warmed", not just "alive".
- **Dispatch** is least-loaded: router-side in-flight count plus the
  last-polled queue depth per replica; ties break round-robin.
- **Failover** rides the ark retry idioms: a transport error reroutes
  the (idempotent, read-only) request to the next-best replica and
  marks the member suspect until a poll clears it; a RETRIABLE serve
  error (queue full, cache exhausted, mid-load) sheds to another
  replica; a TERMINAL error (bad request, unknown model) propagates
  immediately — retrying a malformed request elsewhere helps no one.
- **Coordinated hot swap** is two-phase and version-skew-free: every
  ready replica stages+warms the new version (`prepare_swap`), the
  router verifies all staged manifests are IDENTICAL bytes
  (content-addressed `version_key`), briefly gates new dispatches,
  drains its in-flight window, then flips every replica
  (`commit_swap` — a pointer flip, milliseconds) and reopens. Any
  prepare failure aborts fleet-wide and the old version keeps serving
  everywhere. After a swap the committed `version_key` gates readiness,
  so a replica that missed the flip (or a stale joiner) gets no traffic
  until it catches up.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import flags as _flags
from ..ark.liveness import LeaseTable, QuorumLeaseTable
from ..ark.retry import RetryPolicy
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from ..pserver import rpc as _rpc
from ..serve.errors import (DeadlineExceededError, KVTransferError,
                            ModelUnavailableError, ServeError)
from . import wire as _wire

logger = logging.getLogger(__name__)


@dataclass
class RouterConfig:
    control_endpoint: str = "127.0.0.1:0"   # replicas heartbeat here
    lease_s: float = 3.0                    # membership lease duration
    poll_interval_s: float = 0.5            # readiness poll cadence
    poll: str = "auto"                      # "auto" | "http" | "rpc"
    retry: Optional[RetryPolicy] = None     # failover budget per request
    request_deadline_s: float = 30.0        # per-RPC transport deadline
    swap_drain_timeout_s: float = 30.0      # in-flight drain bound
    pool_max_idle: int = 8                  # idle sockets per replica
    # fluid-pulse opt-in: the router's own health plane (requires the
    # observe flag) with a fleet_membership readiness check
    pulse_port: Optional[int] = None
    # fluid-quorum opt-in: a QuorumClient against the arbiter group.
    # Membership leases become quorum-backed (ark.QuorumLeaseTable): a
    # replica partitioned from the router but still renewing its own
    # member lease at the arbiters (HeartbeatThread(quorum=...)) is not
    # evicted from membership — readiness polling, which requires a
    # live router->replica path anyway, still gates dispatch. None
    # keeps the plain LeaseTable, bit for bit.
    quorum: Optional[object] = None
    quorum_member_prefix: str = "fleet-member:"


class FleetError(ServeError):
    """A fleet-level operation (swap, membership) failed."""


class FleetResult:
    """One routed response: the fetches plus where/what served it.

    `seq` is the router-assigned completion sequence number, taken
    under the router lock BEFORE the request leaves the in-flight
    window: ordering responses by `seq` is the authoritative wire-level
    completion order (client-side timestamps can invert under thread
    scheduling), so the skew gate — every old-version response precedes
    every new-version one across a coordinated swap — is exact."""

    __slots__ = ("outs", "tokens", "version", "version_key", "replica_id",
                 "latency_us", "seq")

    def __init__(self, outs=None, tokens=None, version=None,
                 version_key=None, replica_id=None, latency_us=0.0,
                 seq=0):
        self.outs = outs
        self.tokens = tokens
        self.version = version
        self.version_key = version_key
        self.replica_id = replica_id
        self.latency_us = latency_us
        self.seq = seq


class _Member:
    def __init__(self, replica_id: str, endpoint: str,
                 pulse_port: Optional[int], pool_max_idle: int):
        self.replica_id = replica_id
        self.endpoint = endpoint
        self.pulse_port = pulse_port
        self.pool = _wire.ConnPool(endpoint, max_idle=pool_max_idle)
        self.session: Optional[str] = None
        # fluid-torrent pool assignment ("prefill"|"decode"|"both"),
        # advertised by heartbeat/readiness; "both" = no restriction
        self.role = "both"
        # readiness state, written by the poller (and by failover marks)
        self.ready = False
        self.models: Dict[str, dict] = {}
        self.last_poll = 0.0
        self.suspect = False     # transport error seen; poll must clear
        self.inflight = 0        # router-side concurrent requests

    def close(self):
        self.pool.close()


class FleetRouter(_wire.HardCutServer):
    def __init__(self, config: Optional[RouterConfig] = None):
        super().__init__()
        self.config = config or RouterConfig()
        self.retry = self.config.retry or RetryPolicy(
            max_attempts=3, base_delay=0.01, max_delay=0.25)
        self._lock = threading.RLock()
        self._members: Dict[str, _Member] = {}  # guarded_by: self._lock
        self._lease = (QuorumLeaseTable(
            quorum=self.config.quorum,
            resource_prefix=self.config.quorum_member_prefix)
            if self.config.quorum is not None else LeaseTable())
        self._rr = 0  # guarded_by: self._lock
        # committed fleet version per model (set by swap); gates
        # readiness so a stale replica can never serve mixed versions
        self._desired: Dict[str, str] = {}  # guarded_by: self._lock
        # swap gate per model: set() = dispatch open
        self._gates: Dict[str, threading.Event] = {}  # guarded_by: self._lock
        self._inflight: Dict[str, int] = {}  # guarded_by: self._lock
        self._drain = threading.Condition(self._lock)
        # completion sequence: assigned under the lock while the request
        # is STILL in-flight, so swap's drain orders it before every
        # post-reopen request — the skew gate's exact ordering source
        self._completion_seq = 0  # guarded_by: self._lock
        self.control_endpoint: Optional[str] = None
        self._poller: Optional[threading.Thread] = None
        self.pulse_port: Optional[int] = None
        self._pulse_check_name: Optional[str] = None
        # metrics (serve-style: always on — these are control-plane
        # rates, not hot-path per-step writes)
        self._m_requests = _metrics.counter(
            "fleet_requests_total", "routed requests by model/outcome")
        self._m_latency = _metrics.histogram(
            "fleet_request_latency_us", "router-observed request latency")
        self._m_failovers = _metrics.counter(
            "fleet_failovers_total",
            "requests rerouted after a replica transport failure")
        self._m_sheds = _metrics.counter(
            "fleet_sheds_total",
            "requests rerouted off a backpressuring replica")
        self._m_ready = _metrics.gauge(
            "fleet_replicas_ready", "replicas passing the readiness gate")
        self._m_members = _metrics.gauge(
            "fleet_replicas_registered", "replicas holding a live lease")
        self._m_swaps = _metrics.counter(
            "fleet_swaps_total", "coordinated swaps by outcome")
        # fluid-torrent session affinity: a generating sequence pins to
        # its decode replica for the generation's life
        # guarded_by: self._lock — seq_id -> (replica_id, model)
        self._affinity: Dict[str, Tuple[str, str]] = {}
        self._m_affinity = _metrics.gauge(
            "fleet_affinity_sessions",
            "generating sequences pinned to a decode replica")
        self._m_affinity_released = _metrics.counter(
            "fleet_affinity_released_total",
            "session pins released, by model/reason")
        self._m_tg = _metrics.counter(
            "torrent_generations_total",
            "disaggregated generations by model/outcome")
        self._m_tg_failovers = _metrics.counter(
            "torrent_failovers_total",
            "pinned decode replicas replaced mid-generation "
            "(re-prefill failover), per model")
        self._m_tg_ttft = _metrics.histogram(
            "torrent_ttft_us",
            "end-to-end disaggregated TTFT: route + prefill + KV "
            "stream, per model")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetRouter":
        self.control_endpoint = self._bind_and_accept(
            self.config.control_endpoint,
            f"fleet-router@{self.config.control_endpoint}")
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"fleet-poll@{self.control_endpoint}")
        self._poller.start()
        if self.config.pulse_port is not None:
            from ..observe import health as _health
            from ..observe import pulse as _pulse
            self.pulse_port = _pulse.start_pulse(self.config.pulse_port)
            self._pulse_check_name = f"fleet_membership@{id(self):x}"
            _health.get_engine().register_check(
                self._pulse_check_name, self._pulse_membership_check,
                ready=True)
        logger.info("fleet router control endpoint %s",
                    self.control_endpoint)
        return self

    def close(self):
        if self._pulse_check_name is not None:
            from ..observe import health as _health
            _health.get_engine().unregister_check(self._pulse_check_name)
            self._pulse_check_name = None
        self._hard_cut()
        if self._poller is not None:
            self._poller.join(timeout=5)
        with self._lock:
            members = list(self._members.values())
            self._members.clear()
        for m in members:
            m.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- membership --------------------------------------------------------

    def add_replica(self, endpoint: str, replica_id: Optional[str] = None,
                    pulse_port: Optional[int] = None) -> str:
        """Static registration (loadgen/tests/ops): the replica joins
        with a fresh lease; the poller keeps the lease alive while the
        replica answers readiness probes. Heartbeating replicas register
        themselves through the control endpoint instead."""
        rid = replica_id or f"r@{endpoint}"
        self._register(rid, endpoint, pulse_port, session=None,
                       lease_s=self.config.lease_s)
        self._poll_member_now(rid)
        return rid

    def _register(self, replica_id, endpoint, pulse_port, session,
                  lease_s, role=None):
        with self._lock:
            m = self._members.get(replica_id)
            if m is None or m.endpoint != endpoint:
                if m is not None:
                    m.close()
                m = _Member(replica_id, endpoint, pulse_port,
                            self.config.pool_max_idle)
                self._members[replica_id] = m
            if pulse_port is not None:
                m.pulse_port = pulse_port
            if role:
                m.role = role
            if session is not None and m.session != session:
                # a RESTARTED replica process re-registered under the
                # same id: clear the suspect mark, force a fresh poll
                m.session = session
                m.suspect = True
        self._lease.beat(replica_id, session=session, lease_s=lease_s)

    def remove_replica(self, replica_id: str) -> bool:
        with self._lock:
            m = self._members.pop(replica_id, None)
            pinned = [sid for sid, (rid, _mod) in self._affinity.items()
                      if rid == replica_id]
        for sid in pinned:
            self.release_session(sid, "death")
        self._lease.forget(replica_id)
        if m is not None:
            m.close()
            return True
        return False

    def members(self) -> Dict[str, dict]:
        live = set(self._lease.live())
        with self._lock:
            return {rid: {
                "endpoint": m.endpoint,
                "lease_live": rid in live,
                "ready": m.ready and not m.suspect,
                "suspect": m.suspect,
                "inflight": m.inflight,
                "role": m.role,
                "models": dict(m.models),
                "pulse_port": m.pulse_port,
            } for rid, m in self._members.items()}

    def _live_members(self) -> List[_Member]:
        live = set(self._lease.live())
        with self._lock:
            return [m for rid, m in self._members.items() if rid in live]

    def ready_members(self, model: str,
                      role: Optional[str] = None) -> List[_Member]:
        """Members allowed to take `model` traffic: live lease, ready
        verdict, not suspect, model present+warmed, and — once a swap
        committed a fleet version — the matching version_key. `role`
        (fluid-torrent) keeps only members of that pool; "both" members
        always qualify."""
        with self._lock:
            want = self._desired.get(model)
        out = []
        for m in self._live_members():
            if not m.ready or m.suspect:
                continue
            if role is not None and m.role not in (role, "both"):
                continue
            d = m.models.get(model)
            if not d or not d.get("warmed"):
                continue
            if want is not None and d.get("version_key") != want:
                continue
            out.append(m)
        return out

    # -- readiness polling -------------------------------------------------

    def _poll_loop(self):
        while not self._stop.wait(self.config.poll_interval_s):
            with self._lock:
                snapshot = list(self._members.values())
            for m in snapshot:
                if self._stop.is_set():
                    return
                self._poll_member(m)
            ready_by_model: Dict[str, int] = {}
            with self._lock:
                models = {name for m in self._members.values()
                          for name in m.models}
            for name in models:
                ready_by_model[name] = len(self.ready_members(name))
                self._m_ready.set(ready_by_model[name], model=name)
            self._m_members.set(len(self._live_members()))

    def _poll_member_now(self, replica_id: str):
        with self._lock:
            m = self._members.get(replica_id)
        if m is not None:
            self._poll_member(m)

    def _poll_member(self, m: _Member):
        doc = None
        try:
            if m.pulse_port and self.config.poll in ("auto", "http"):
                doc = self._poll_http(m)
            else:
                doc = _wire.call(m.pool, "readyz", {}, deadline_s=2.0)
        except Exception as e:
            logger.debug("fleet poll of %s failed: %r", m.replica_id, e)
            with self._lock:
                m.ready = False
                m.last_poll = time.monotonic()
            return
        with self._lock:
            m.ready = doc.get("status") == "ok"
            m.models = dict(doc.get("models") or {})
            if doc.get("role"):
                m.role = doc["role"]
            m.suspect = False
            m.last_poll = time.monotonic()
        # probe evidence of liveness: a poll that answered renews the
        # lease (static members have no heartbeat loop of their own)
        self._lease.beat(m.replica_id, session=m.session,
                         lease_s=self.config.lease_s)

    def _poll_http(self, m: _Member) -> dict:
        """The fluid-pulse /readyz HTTP contract: 200/503 with a verdict
        body whose serve_queues check detail carries the per-model
        version/warmed/depth facts (serve.InferenceServer.model_detail).
        503 still parses — unready is a verdict, not a transport error."""
        import json
        import urllib.error
        import urllib.request

        url = f"http://127.0.0.1:{m.pulse_port}/readyz"
        host = m.endpoint.split(":")[0]
        if host not in ("127.0.0.1", "localhost", "0.0.0.0"):
            url = f"http://{host}:{m.pulse_port}/readyz"
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                doc = json.loads(r.read())
        except urllib.error.HTTPError as e:
            doc = json.loads(e.read())
        models: Dict[str, dict] = {}
        for name, check in (doc.get("checks") or {}).items():
            if name.startswith("serve_queues"):
                models.update(check.get("detail") or {})
        return {"status": doc.get("status"), "models": models}

    # -- control endpoint (replica heartbeats) -----------------------------
    # accept/teardown plumbing: wire.HardCutServer

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                msg = _rpc.recv_msg(conn)
            except (ConnectionError, EOFError, OSError):
                return
            if self._stop.is_set():
                return
            try:
                cmd, payload = msg[0], msg[1]
            except (TypeError, IndexError):
                _rpc.send_msg(conn, ("err", "MalformedFrame"))
                continue
            try:
                reply = self._control_dispatch(cmd, payload)
            except Exception as e:
                reply = ("err", f"{type(e).__name__}: {e}")
            try:
                _rpc.send_msg(conn, reply)
            except (ConnectionError, OSError):
                return

    def _control_dispatch(self, cmd, p):
        if cmd == "replica_heartbeat":
            self._register(p["replica_id"], p["endpoint"],
                           p.get("pulse_port"), p.get("session"),
                           float(p.get("lease_s") or self.config.lease_s),
                           role=p.get("role"))
            with self._lock:
                n_members = len(self._members)
            return ("ok", {"members": n_members})
        if cmd == "replica_leave":
            return ("ok", {"removed":
                           self.remove_replica(p["replica_id"])})
        if cmd == "router_stats":
            return ("ok", self.stats())
        if cmd == "ping":
            return ("ok", {"control": self.control_endpoint})
        raise ValueError(f"unknown fleet router command {cmd!r}")

    # -- dispatch ----------------------------------------------------------

    def _gate(self, model: str) -> threading.Event:
        with self._lock:
            g = self._gates.get(model)
            if g is None:
                g = self._gates[model] = threading.Event()
                g.set()
            return g

    def _pick(self, model: str, exclude: set,
              role: Optional[str] = None) -> Optional[_Member]:
        """Least-loaded among ready members: router in-flight plus the
        last-polled queue depth; round-robin among ties."""
        cands = [m for m in self.ready_members(model, role=role)
                 if m.replica_id not in exclude]
        if not cands:
            return None
        with self._lock:
            def score(m: _Member):
                depth = (m.models.get(model) or {}).get("depth") or 0
                return m.inflight + depth
            best = min(score(m) for m in cands)
            tied = [m for m in cands if score(m) == best]
            self._rr += 1
            return tied[self._rr % len(tied)]

    def _request(self, model: str, cmd: str, payload: dict,
                 role: Optional[str] = None) -> FleetResult:
        """The routed request core: gate, pick, call, classify, retry.

        fluid-horizon entry point: with the observe flag on, the whole
        routed request runs under a `fleet:{cmd}` span — the trace ROOT
        when no caller context is ambient — so every wire.call to a
        replica (and everything the replica fans out to: batcher,
        sparse PSClient, pserver) parents under one trace."""
        if _flags.get_flag("observe"):
            with _xray.span(f"fleet:{cmd}", cat="fleet", model=model,
                            cmd=cmd):
                return self._request_inner(model, cmd, payload, role)
        return self._request_inner(model, cmd, payload, role)

    def _request_inner(self, model: str, cmd: str, payload: dict,
                       role: Optional[str] = None) -> FleetResult:
        payload = {"model": model, **payload}
        gate_deadline = time.monotonic() + \
            self.config.swap_drain_timeout_s + 5.0
        while True:
            gate = self._gate(model)
            if not gate.wait(timeout=max(
                    0.01, gate_deadline - time.monotonic())):
                raise ModelUnavailableError(
                    f"model {model!r}: dispatch gated by a coordinated "
                    f"swap that never completed")
            with self._lock:
                # re-check UNDER THE LOCK: a swap's gate.clear() racing
                # the bare wait() would otherwise let this request slip
                # in unregistered — invisible to the swap's drain, free
                # to execute on an unflipped replica mid-flip (exactly
                # the mixed-version window the drain exists to close)
                if gate.is_set():
                    self._inflight[model] = \
                        self._inflight.get(model, 0) + 1
                    break
            if time.monotonic() >= gate_deadline:
                raise ModelUnavailableError(
                    f"model {model!r}: dispatch gated by a coordinated "
                    f"swap that never completed")
        t0 = time.perf_counter()
        exclude: set = set()
        attempt = 0
        last_err: Optional[BaseException] = None
        try:
            while True:
                m = self._pick(model, exclude, role=role)
                if m is None and not exclude and \
                        attempt <= self.retry.max_attempts:
                    # nobody ready RIGHT NOW but nothing failed either
                    # (a swap just reopened, a poll is in flight, a
                    # replica is joining): wait a poll beat inside the
                    # retry budget instead of bouncing the request
                    attempt += 1
                    time.sleep(min(self.config.poll_interval_s, 0.25))
                    continue
                if m is None:
                    self._m_requests.inc(model=model, outcome="no_replica")
                    if last_err is not None:
                        raise last_err
                    with self._lock:
                        known = sorted(self._members)
                    raise ModelUnavailableError(
                        f"model {model!r}: no ready replica "
                        f"(members: {known})")
                with self._lock:
                    m.inflight += 1
                try:
                    value = _wire.call(
                        m.pool, cmd, payload,
                        deadline_s=self.config.request_deadline_s)
                    dt_us = (time.perf_counter() - t0) * 1e6
                    with self._lock:
                        self._completion_seq += 1
                        seq = self._completion_seq
                    self._m_requests.inc(model=model, outcome="ok")
                    self._m_latency.observe(dt_us, model=model)
                    return FleetResult(
                        outs=value.get("outs"),
                        tokens=value.get("tokens"),
                        version=value.get("version"),
                        version_key=value.get("version_key"),
                        replica_id=value.get("replica_id", m.replica_id),
                        latency_us=dt_us, seq=seq)
                except (ConnectionError, EOFError, OSError) as e:
                    # transport death: the replica is gone or mid-kill.
                    # infer/generate are read-only and idempotent, so a
                    # recv-phase failure is safe to replay on a peer
                    # (the PSClient read-failover rule).
                    last_err = e
                    exclude.add(m.replica_id)
                    with self._lock:
                        m.suspect = True   # a fresh poll must clear it
                    self._m_failovers.inc(model=model, frm=m.replica_id)
                    logger.warning(
                        "fleet: %s failed %s (%r) — failing over",
                        m.replica_id, cmd, e)
                except ServeError as e:
                    if isinstance(e, KVTransferError):
                        # the PREFILL half failed to deliver KV to its
                        # pinned RECEIVER: rerouting the prefill to
                        # another replica cannot fix a dead decode
                        # replica. Propagate now — the torrent
                        # orchestrator owns that failover (it releases
                        # the pin and re-prefills against a fresh
                        # decode replica).
                        self._m_requests.inc(model=model,
                                             outcome="kv_transfer")
                        raise
                    if not getattr(e, "retriable", False) or \
                            isinstance(e, DeadlineExceededError):
                        # terminal (bad request, unknown model) — or a
                        # deadline that already burned the caller's
                        # budget: rerouting cannot help
                        self._m_requests.inc(model=model,
                                             outcome="terminal_error")
                        raise
                    # retriable backpressure: shed to another replica
                    last_err = e
                    exclude.add(m.replica_id)
                    self._m_sheds.inc(model=model, frm=m.replica_id,
                                      reason=type(e).__name__)
                finally:
                    with self._lock:
                        m.inflight -= 1
                attempt += 1
                if attempt > self.retry.max_attempts:
                    self._m_requests.inc(model=model, outcome="exhausted")
                    raise last_err
                delay = self.retry.backoff(attempt - 1)
                if delay and not self.ready_members(model):
                    time.sleep(min(delay, 0.25))
        finally:
            with self._lock:
                self._inflight[model] -= 1
                self._drain.notify_all()

    def infer(self, model: str, feed: dict,
              deadline_ms: Optional[float] = None) -> FleetResult:
        """Route one one-shot inference request; returns a FleetResult
        whose .outs is the fetch list and .version/.version_key name the
        version that EXECUTED it (the skew gate's evidence)."""
        return self._request(model, "infer",
                             {"feed": feed, "deadline_ms": deadline_ms})

    def generate(self, model: str, prompt, max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None) -> FleetResult:
        """Route one generation; in-flight generations stay pinned to
        their version per replica (the decode engine's guarantee)."""
        return self._request(
            model, "generate",
            {"prompt": prompt, "max_new_tokens": max_new_tokens,
             "deadline_ms": deadline_ms})

    # -- fluid-torrent: disaggregated generation ---------------------------

    def pin_session(self, seq_id: str, model: str,
                    exclude: frozenset = frozenset()) -> _Member:
        """Pin a generative session to a decode replica (session
        affinity): least-loaded among ready decode-pool members, held
        until `release_session`. The pin is the decode half of a
        disaggregated generation — the prefill replica streams KV to
        exactly this member, and every subsequent hop (collect, cancel)
        dispatches to it directly, no re-pick."""
        m = self._pick(model, set(exclude), role="decode")
        if m is None:
            raise ModelUnavailableError(
                f"model {model!r}: no ready decode replica to pin "
                f"session {seq_id!r} (excluded: {sorted(exclude)})")
        with self._lock:
            self._affinity[seq_id] = (m.replica_id, model)
            self._m_affinity.set(float(len(self._affinity)))
        return m

    def session_replica(self, seq_id: str) -> Optional[str]:
        """The replica_id a session is pinned to, or None."""
        with self._lock:
            pin = self._affinity.get(seq_id)
        return pin[0] if pin else None

    def release_session(self, seq_id: str, reason: str) -> bool:
        """Drop a session pin (EOS, length, cancel, error, or replica
        death). Idempotent; returns whether a pin existed."""
        with self._lock:
            pin = self._affinity.pop(seq_id, None)
            self._m_affinity.set(float(len(self._affinity)))
        if pin is None:
            return False
        self._m_affinity_released.inc(model=pin[1], reason=reason)
        return True

    def _call_member(self, m: _Member, model: str, cmd: str,
                     payload: dict, deadline_s: Optional[float] = None):
        """Pinned dispatch: one wire call to a SPECIFIC member, no
        pick, no retry, no shed — affinity means the request must land
        here or fail so the orchestrator can re-pin. Counts against the
        member's least-loaded in-flight but not the swap drain window
        (see docs/TORRENT.md for why that's acceptable)."""
        with self._lock:
            m.inflight += 1
        try:
            return _wire.call(
                m.pool, cmd, {"model": model, **payload},
                deadline_s=deadline_s or self.config.request_deadline_s)
        finally:
            with self._lock:
                m.inflight -= 1

    def generate_torrent(self, model: str, prompt,
                         max_new_tokens: int = 16,
                         deadline_ms: Optional[float] = None,
                         seq_id: Optional[str] = None) -> FleetResult:
        """One DISAGGREGATED generation: pin a decode replica, route the
        prefill half to the prefill pool (which streams KV straight to
        the pinned member), then collect the finished tokens from the
        decode replica.

        Failover: a decode replica that dies mid-generation (transport
        error on collect, KVTransferError from the stream, retriable
        serve error) is excluded, the pin released, and the WHOLE
        generation re-prefilled against a fresh decode replica — safe
        because greedy decoding is deterministic, so the re-run
        reproduces the identical token sequence: completed tokens are
        never lost, only recomputed. Terminal errors propagate."""
        sid = seq_id or f"tg-{uuid.uuid4().hex[:12]}"
        if _flags.get_flag("observe"):
            with _xray.span("fleet:torrent_generate", cat="fleet",
                            model=model, seq=sid):
                return self._generate_torrent_inner(
                    model, prompt, max_new_tokens, deadline_ms, sid)
        return self._generate_torrent_inner(
            model, prompt, max_new_tokens, deadline_ms, sid)

    def _generate_torrent_inner(self, model: str, prompt,
                                max_new: int,
                                deadline_ms: Optional[float],
                                sid: str) -> FleetResult:
        t0 = time.perf_counter()
        bad_decodes: set = set()
        attempt = 0
        while True:
            attempt += 1
            # resolve the pin: reuse a live existing pin (resubmitted
            # seq_id), else pick a fresh decode replica
            m = None
            with self._lock:
                pin = self._affinity.get(sid)
                if pin is not None:
                    cand = self._members.get(pin[0])
                    if cand is not None and \
                            cand.replica_id not in bad_decodes:
                        m = cand
            if m is None:
                self.release_session(sid, "death")
                m = self.pin_session(sid, model,
                                     exclude=frozenset(bad_decodes))
            try:
                pre = self._request(
                    model, "torrent_prefill",
                    {"prompt": prompt, "max_new_tokens": max_new,
                     "seq_id": sid, "decode_endpoint": m.endpoint,
                     "deadline_ms": deadline_ms},
                    role="prefill")
                # end-to-end disaggregated TTFT: route + prefill + KV
                # stream — the first token exists (on the decode
                # replica) the moment the stream commits
                self._m_tg_ttft.observe(
                    (time.perf_counter() - t0) * 1e6, model=model)
                value = self._call_member(
                    m, model, "torrent_collect",
                    {"seq_id": sid, "deadline_ms": deadline_ms})
            except (ConnectionError, EOFError, OSError,
                    KVTransferError) as e:
                # the pinned DECODE replica is unreachable (directly on
                # collect, or via the prefill's stream): exclude it,
                # drop the pin, re-prefill elsewhere
                self._fail_over_decode(model, sid, m, bad_decodes, e)
                if attempt > self.retry.max_attempts:
                    self._m_tg.inc(model=model, outcome="exhausted")
                    raise KVTransferError(
                        f"session {sid!r}: no decode replica survived "
                        f"{attempt} attempts") from e
                continue
            except ServeError as e:
                if getattr(e, "retriable", False) and \
                        not isinstance(e, DeadlineExceededError):
                    # decode-side backpressure (admission full on the
                    # pinned replica): re-pin onto another decode
                    self._fail_over_decode(model, sid, m, bad_decodes, e)
                    if attempt > self.retry.max_attempts:
                        self._m_tg.inc(model=model, outcome="exhausted")
                        raise
                    continue
                self.release_session(sid, "error")
                self._m_tg.inc(model=model, outcome="terminal_error")
                raise
            self.release_session(
                sid, str(value.get("finish_reason", "eos")))
            self._m_tg.inc(model=model, outcome="ok")
            dt_us = (time.perf_counter() - t0) * 1e6
            with self._lock:
                self._completion_seq += 1
                seq = self._completion_seq
            return FleetResult(
                outs={"prefill": pre.outs,
                      "finish_reason": value.get("finish_reason"),
                      "ttft_us": value.get("ttft_us")},
                tokens=value.get("tokens"),
                version=value.get("version"),
                version_key=value.get("version_key"),
                replica_id=value.get("replica_id", m.replica_id),
                latency_us=dt_us, seq=seq)

    def _fail_over_decode(self, model: str, sid: str, m: _Member,
                          bad_decodes: set, err: BaseException):
        """Shared decode-failover bookkeeping for generate_torrent."""
        bad_decodes.add(m.replica_id)
        with self._lock:
            m.suspect = True   # a fresh poll must clear it
        self.release_session(sid, "death")
        self._m_tg_failovers.inc(model=model, frm=m.replica_id)
        logger.warning(
            "fleet-torrent: decode %s lost session %s (%r) — "
            "re-prefilling elsewhere", m.replica_id, sid, err)

    def cancel_torrent(self, seq_id: str) -> bool:
        """Cancel a disaggregated session: release the pin and
        best-effort drop any staged/finished KV on the pinned replica.
        Returns whether a pin existed."""
        with self._lock:
            pin = self._affinity.get(seq_id)
            m = self._members.get(pin[0]) if pin else None
        had = self.release_session(seq_id, "cancel")
        if m is not None:
            try:
                self._call_member(m, pin[1], "torrent_cancel",
                                  {"seq_id": seq_id}, deadline_s=5.0)
            except Exception:
                pass   # the pin is gone either way
        return had

    # -- coordinated hot swap ---------------------------------------------

    def swap(self, model: str, dirname: Optional[str] = None) -> dict:
        """Version-skew-free fleet swap (see module docstring). Returns
        a report dict; raises FleetError (old version keeps serving
        everywhere) on any prepare/verify failure."""
        t0 = time.perf_counter()
        targets = self.ready_members(model)
        if not targets:
            self._m_swaps.inc(model=model, outcome="no_replica")
            raise FleetError(
                f"swap({model!r}): no ready replica to swap")

        # phase 1: stage + warm EVERYWHERE (parallel; slowest replica
        # bounds the phase, traffic keeps flowing on the old version)
        staged: Dict[str, dict] = {}
        errors: Dict[str, str] = {}

        def _prepare(m: _Member):
            try:
                staged[m.replica_id] = _wire.call(
                    m.pool, "prepare_swap",
                    {"model": model, "dirname": dirname},
                    deadline_s=max(self.config.request_deadline_s, 120.0))
            except Exception as e:
                errors[m.replica_id] = f"{type(e).__name__}: {e}"

        threads = [threading.Thread(target=_prepare, args=(m,))
                   for m in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        keys = {d.get("version_key") for d in staged.values()}
        if errors or len(keys) != 1 or None in keys:
            for m in targets:
                if m.replica_id in staged:
                    try:
                        _wire.call(m.pool, "abort_swap", {"model": model},
                                   deadline_s=10.0)
                    except Exception:
                        pass
            self._m_swaps.inc(model=model, outcome="prepare_failed")
            raise FleetError(
                f"swap({model!r}) aborted — old version keeps serving: "
                f"prepare errors {errors or 'none'}, staged keys "
                f"{sorted(k for k in keys if k)}"
                + (" (replicas staged DIFFERENT content)"
                   if len(keys) > 1 else ""))
        new_key = keys.pop()

        # phase 2: gate new dispatches and drain the router's in-flight
        # window — responses already executing finish on the OLD version
        # BEFORE any replica flips, so no client can observe new-then-old
        gate = self._gate(model)
        gate.clear()
        committed: Dict[str, dict] = {}
        try:
            deadline = time.monotonic() + self.config.swap_drain_timeout_s
            with self._lock:
                while self._inflight.get(model, 0) > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._m_swaps.inc(model=model,
                                          outcome="drain_timeout")
                        raise FleetError(
                            f"swap({model!r}): {self._inflight[model]} "
                            f"requests failed to drain in "
                            f"{self.config.swap_drain_timeout_s}s — "
                            f"aborted, old version keeps serving")
                    self._drain.wait(remaining)

            # phase 3: flip everywhere (pure pointer flips — staged
            # versions are already warmed)
            flip_errors: Dict[str, str] = {}
            for m in targets:
                try:
                    committed[m.replica_id] = _wire.call(
                        m.pool, "commit_swap", {"model": model},
                        deadline_s=30.0)
                except Exception as e:
                    flip_errors[m.replica_id] = f"{type(e).__name__}: {e}"
                    with self._lock:
                        m.suspect = True
            if not committed:
                self._m_swaps.inc(model=model, outcome="commit_failed")
                raise FleetError(
                    f"swap({model!r}): every commit failed "
                    f"({flip_errors}) — fleet stays on the old version")
            # partial success: best-effort abort on the replicas whose
            # flip failed, or their staged (fully loaded + warmed)
            # version would sit in memory indefinitely; if the commit
            # actually landed and only the reply died, the abort is a
            # no-op and the replica rejoins on the new version_key
            for m in targets:
                if m.replica_id in staged and \
                        m.replica_id not in committed:
                    try:
                        _wire.call(m.pool, "abort_swap", {"model": model},
                                   deadline_s=10.0)
                    except Exception:
                        pass
            # refresh membership detail BEFORE the gate reopens, so the
            # first gated-out request dispatches on the new version_key
            # instead of finding a momentarily-empty ready set
            for m in targets:
                if m.replica_id in committed:
                    self._poll_member(m)
            # the fleet version is now new_key: any replica that failed
            # its flip reports a stale version_key and the readiness
            # gate keeps it out of dispatch until it catches up
            # (under the lock: dispatch threads read it in
            # ready_members, and the RLock write also publishes the
            # membership details _poll_member refreshed above)
            with self._lock:
                self._desired[model] = new_key
        except FleetError:
            for m in targets:
                if m.replica_id not in committed:
                    try:
                        _wire.call(m.pool, "abort_swap", {"model": model},
                                   deadline_s=10.0)
                    except Exception:
                        pass
            raise
        finally:
            gate.set()
        self._m_swaps.inc(model=model, outcome="ok")
        report = {
            "model": model,
            "version_key": new_key,
            "replicas": sorted(committed),
            "failed_commits": sorted(set(staged) - set(committed)),
            "wall_s": round(time.perf_counter() - t0, 3),
        }
        logger.info("fleet: coordinated swap of %r -> %s across %d "
                    "replicas in %.2fs", model, new_key[:12],
                    len(committed), report["wall_s"])
        return report

    # -- introspection -----------------------------------------------------

    def _pulse_membership_check(self):
        """fluid-pulse check: every model the fleet serves must have at
        least one ready replica."""
        members = self.members()
        models: Dict[str, int] = {}
        for m in members.values():
            for name in m["models"]:
                models.setdefault(name, 0)
        for name in models:
            models[name] = len(self.ready_members(name))
        ok = all(n > 0 for n in models.values()) if models else True
        with self._lock:
            desired = dict(self._desired)
        return ok, {"ready_by_model": models,
                    "members": {rid: {"ready": m["ready"],
                                      "endpoint": m["endpoint"]}
                                for rid, m in members.items()},
                    "desired_versions": desired}

    def stats(self) -> dict:
        with self._lock:
            inflight = dict(self._inflight)
            desired = dict(self._desired)
        return {
            "control_endpoint": self.control_endpoint,
            "members": self.members(),
            "inflight": inflight,
            "desired_versions": desired,
            "ts": time.time(),
        }
