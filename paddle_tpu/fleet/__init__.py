"""fluid-fleet: the multi-replica serving tier (see docs/FLEET.md).

`InferenceServer` scales one process; the north star's "heavy traffic
from millions of users" needs a FLEET. Four pieces, each reusing a
subsystem the repo already trusts:

- `fleet.router`  — FleetRouter: ark-lease membership, pulse-/readyz-
  gated readiness ("right version, warmed"), least-loaded dispatch,
  retry/failover with retriable-vs-terminal classification, and the
  two-phase version-skew-free coordinated hot swap;
- `fleet.replica` — ReplicaServer: the TCP RPC front of one
  InferenceServer (requests tagged with the executing version, swap
  prepare/commit/abort, readyz, per-process observatory stats) plus the
  membership heartbeat;
- `fleet.sparse`  — the serve-time distributed embedding read path:
  models whose lookup tables live only in pserver shards
  (`save_sparse_inference_model`) pull rows at inference through a
  read-only wire-codec PSClient and a bounded, version-keyed row cache;
- `fleet.wire`    — the pooled framed transport both sides ride.

Drills: `tools/serve_loadgen.py --replicas N` (QPS scaling + skew-free
swap under load), `tools/chaos_drill.py --scenario replica_kill` (a
SIGKILLed replica degrades p99, not availability); bench.py's `fleet`
segment records qps_scaling and p99_under_kill.
"""

from __future__ import annotations

from .replica import ReplicaServer  # noqa: F401
from .router import (FleetError, FleetResult, FleetRouter,  # noqa: F401
                     RouterConfig)
from .sparse import (DEFAULT_CACHE_ROWS, RowCache,  # noqa: F401
                     SparseLookupPlan, SparseServeConfig,
                     save_sparse_inference_model, sparse_table_specs)
from .wire import ConnPool  # noqa: F401
