"""fluid-fleet transport: pooled, framed RPC between router and replicas.

Rides the pserver rpc framing (length-prefixed restricted-pickle frames,
`pserver/rpc.py`) so the fleet speaks the wire the repo already hardens
— but with a CONNECTION POOL per peer instead of PSClient's one-socket-
per-endpoint: serving requests to one replica must overlap (a router
thread per client request checks a socket out, so N concurrent requests
to a replica ride N sockets), where the training client's per-endpoint
lock was the right call for ordered push/pull streams.

Reply taxonomy (the retriable-vs-terminal classification the router's
failover policy keys on):

    ("ok", value)                     success
    ("err_serve", {type, msg,         a serve.errors.ServeError — the
                   retriable})        name maps back to the class, so
                                      QueueFullError raised on a replica
                                      IS QueueFullError at the router
    ("err", "Type: msg")              anything else (a bug — terminal)

Transport failures (ConnectionError/EOFError/OSError) surface as-is;
the caller decides whether another peer can answer.
"""

from __future__ import annotations

import socket as _socket
import struct as _struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import flags as _flags
from ..observe import xray as _xray
from ..pserver import rpc as _rpc
from ..serve import errors as serve_errors

#: name -> class for reconstructing serve errors across the wire
SERVE_ERRORS: Dict[str, type] = {
    c.__name__: c
    for c in (serve_errors.ServeError, serve_errors.ModelNotFoundError,
              serve_errors.ModelUnavailableError,
              serve_errors.BadRequestError, serve_errors.QueueFullError,
              serve_errors.DeadlineExceededError,
              serve_errors.CacheExhaustedError,
              serve_errors.KVTransferError)
}


def serve_error_reply(e: serve_errors.ServeError) -> Tuple[str, dict]:
    """The ("err_serve", ...) reply for a ServeError raised in a replica
    handler."""
    return ("err_serve", {"type": type(e).__name__, "msg": str(e),
                          "retriable": bool(getattr(e, "retriable",
                                                    False))})


def raise_serve_error(payload: dict):
    """Rebuild (and raise) the replica-side ServeError at the caller."""
    cls = SERVE_ERRORS.get(payload.get("type"), serve_errors.ServeError)
    raise cls(payload.get("msg", "remote serve error"))


class HardCutServer:
    """The pserver accept-loop + hard-teardown idiom, factored ONCE for
    both fleet sides (FleetRouter's control endpoint and ReplicaServer):
    bind an ephemeral-capable listener, spawn a daemon thread per
    accepted connection, track live sockets, and on `_hard_cut()` die
    like a killed process — listener shut down, every live connection
    RST-closed (SO_LINGER 0) so blocked peers see the death NOW instead
    of a FIN_WAIT_2 hang. Subclasses implement `_serve_conn(conn)` (the
    per-connection request/reply loop; the accept plumbing handles
    tracking and close)."""

    def __init__(self):
        self._listener: Optional[_socket.socket] = None
        self._conns: set = set()  # guarded_by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    def _bind_and_accept(self, endpoint: str, thread_name: str) -> str:
        """Bind `endpoint` (port 0 = ephemeral), start the accept loop;
        returns the bound host:port."""
        host, port = _rpc.parse_endpoint(endpoint)
        self._listener = _socket.socket(_socket.AF_INET,
                                        _socket.SOCK_STREAM)
        self._listener.setsockopt(_socket.SOL_SOCKET,
                                  _socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        bound = f"{host}:{self._listener.getsockname()[1]}"
        self._listener.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=thread_name).start()
        return bound

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._conn_entry, args=(conn,),
                             daemon=True).start()

    def _conn_entry(self, conn):
        try:
            self._serve_conn(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _serve_conn(self, conn):   # pragma: no cover - abstract
        raise NotImplementedError

    def _hard_cut(self):
        """Kill the transport NOW (listener + every live connection)."""
        self._stop.set()
        if self._listener is not None:
            for f in ("shutdown", "close"):
                try:
                    (self._listener.shutdown(_socket.SHUT_RDWR)
                     if f == "shutdown" else self._listener.close())
                except OSError:
                    pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                             _struct.pack("ii", 1, 0))
            except OSError:
                pass
            for f in ("shutdown", "close"):
                try:
                    (c.shutdown(_socket.SHUT_RDWR) if f == "shutdown"
                     else c.close())
                except OSError:
                    pass


class ConnPool:
    """A small stack of idle sockets to one endpoint. checkout() hands a
    connected socket out (reusing an idle one when available); checkin()
    returns it; a socket that saw a transport error is closed, never
    pooled. Idle sockets beyond `max_idle` are closed on checkin."""

    def __init__(self, endpoint: str, max_idle: int = 8,
                 connect_timeout: float = 5.0):
        self.endpoint = endpoint
        self.max_idle = int(max_idle)
        self.connect_timeout = float(connect_timeout)
        self._lock = threading.Lock()
        self._idle: List = []
        self._closed = False

    def checkout(self):
        with self._lock:
            if self._closed:
                raise ConnectionError(
                    f"pool to {self.endpoint} is closed")
            if self._idle:
                return self._idle.pop()
        return _rpc.connect(self.endpoint, timeout=self.connect_timeout)

    def checkin(self, sock, broken: bool = False):
        if sock is None:
            return
        if broken:
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            if not self._closed and len(self._idle) < self.max_idle:
                self._idle.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close(self):
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for s in idle:
            try:
                s.close()
            except OSError:
                pass


def call(pool: ConnPool, cmd: str, payload: Optional[dict] = None,
         deadline_s: Optional[float] = None):
    """One request/reply over a pooled socket. Returns the reply VALUE;
    raises the mapped ServeError for ("err_serve", ...), RuntimeError
    for ("err", ...), and lets transport errors propagate (the socket is
    discarded either way on failure).

    fluid-xray: with the observe flag on, the frame carries a fresh
    child of the ambient traceparent as the optional third element, and
    the call records that child as a `fleet_call:<cmd>` span — the
    replica handler's `replica:<cmd>` span parents under it, so the
    stitched fleet timeline has no orphaned hop (exactly the pserver
    client's per-attempt `rpc_client` shape)."""
    sock = pool.checkout()
    broken = True
    ctx = _xray.child_of() if _flags.get_flag("observe") else None
    ts_wall, t0 = time.time(), time.perf_counter()
    status = "transport_error"
    try:
        if deadline_s is not None:
            sock.settimeout(deadline_s)
        frame = (cmd, payload or {})
        if ctx is not None:
            frame = (cmd, payload or {}, _xray.to_wire(ctx))
        _rpc.send_msg(sock, frame)
        status, value = _rpc.recv_msg(sock)
        if deadline_s is not None:
            sock.settimeout(None)
        broken = False
        if status == "ok":
            return value
        if status == "err_serve":
            raise_serve_error(value)
        raise RuntimeError(f"fleet peer {pool.endpoint} {cmd}: {value}")
    finally:
        if ctx is not None:
            _xray.record_span(
                f"fleet_call:{cmd}", ctx, ts_wall,
                time.perf_counter() - t0, cat="fleet", cmd=cmd,
                endpoint=pool.endpoint, status=status)
        pool.checkin(sock, broken=broken)
