"""fluid-fleet replica: the RPC front of one InferenceServer.

One serving process = one ``InferenceServer`` (registry + batchers +
engines, exactly as fluid-serve built it) + one ``ReplicaServer`` that
exposes it on a TCP endpoint the router can dispatch to:

    infer / generate       the request path (replies carry the VERSION
                           that executed the request — the router's
                           skew gate is built on this tag)
    readyz                 the same per-model verdict the pulse /readyz
                           HTTP endpoint serves (version, warmed, queue
                           depth/saturation) — the RPC fallback for
                           deployments without the observe flag
    prepare_swap /         the replica half of the coordinated swap:
    commit_swap /          stage+warm now, flip on the router's word,
    abort_swap             roll back if any peer failed
    fleet_stats            serving stats + the observatory's unexpected-
                           recompile count, so a fleet drill can gate
                           "zero steady-state recompiles" across every
                           replica process

Membership: the replica heartbeats the router's control endpoint on the
ark lease-renewal rule (``HeartbeatThread(beat=...)``, renew at a third
of the lease) — a SIGKILLed replica simply stops renewing and the
router's ``LeaseTable`` expires it; an explicit ``leave`` is sent on
clean stop. ``stop()`` is a hard cut (listener + live connections RST),
mirroring ``ParameterServer.stop`` so chaos drills can treat it as a
process death.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Optional

import numpy as np

from .. import flags as _flags
from ..ark.heartbeat import HeartbeatThread
from ..observe import steplog as _steplog
from ..observe import xray as _xray
from ..pserver import rpc as _rpc
from ..serve.errors import ServeError
from ..serve.server import InferenceServer
from ..torrent.prefill import prefill_and_stream
from ..torrent.stream import KVStreamReceiver
from . import wire as _wire

logger = logging.getLogger(__name__)

_ROLES = ("prefill", "decode", "both")


class ReplicaServer(_wire.HardCutServer):
    def __init__(self, server: InferenceServer, endpoint: str = "127.0.0.1:0",
                 replica_id: Optional[str] = None,
                 router_endpoint: Optional[str] = None,
                 lease_s: float = 3.0,
                 simulate_device_ms: float = 0.0,
                 quorum=None,
                 quorum_member_prefix: str = "fleet-member:",
                 role: str = "both"):
        """`quorum` (fluid-quorum, a `QuorumClient`) makes this
        replica's membership partition-safe: each heartbeat round also
        renews its OWN lease at the arbiter group under
        `<quorum_member_prefix><replica_id>` with the replica id as the
        holder — exactly what a router armed with
        `RouterConfig(quorum=..., quorum_member_prefix=...)` verifies,
        so a replica that lost its path to the router (but not to the
        arbiters) is not falsely evicted from membership.

        `simulate_device_ms` is a REHEARSAL-RIG knob (CPU containers,
        often single-core): it sleeps that long per served request,
        standing in for the TPU device time a real replica spends off
        the host CPU. It is what lets the multi-replica loadgen measure
        ROUTER/RPC scaling on a 1-core rig — the drill records it, and
        it must be 0 in any real deployment.

        `role` is the fluid-torrent pool assignment this replica
        advertises (heartbeat + readiness): "prefill" and "decode"
        replicas take only their half of disaggregated traffic from
        `FleetRouter.generate_torrent`; "both" (default) is eligible for
        everything, including classic co-located `generate`. The role is
        a ROUTING hint, not an enforcement boundary — every handler
        stays available, so an operator can drain a pool by re-roling
        without stranding in-flight work."""
        super().__init__()
        if role not in _ROLES:
            raise ValueError(f"role must be one of {_ROLES}, got {role!r}")
        self.server = server
        self.replica_id = replica_id or f"r-{uuid.uuid4().hex[:8]}"
        self.session = uuid.uuid4().hex
        self.role = role
        self.router_endpoint = router_endpoint
        self.lease_s = float(lease_s)
        self.simulate_device_s = max(0.0, float(simulate_device_ms)) / 1e3
        # ONE simulated device per replica: concurrent requests must
        # SERIALIZE their simulated device time (a chip runs one batch
        # at a time) or a single replica would show no throughput
        # ceiling and the scaling drill would measure nothing
        self._device_lock = threading.Lock()
        self.endpoint = endpoint
        self.quorum = quorum
        self.quorum_member_prefix = str(quorum_member_prefix)
        self._heartbeat: Optional[HeartbeatThread] = None
        self._router_pool: Optional[_wire.ConnPool] = None
        # fluid-torrent: the decode half's staging table, and the
        # prefill half's connection pools to decode replicas
        self._kv_recv = KVStreamReceiver(self._torrent_admit)
        self._torrent_lock = threading.Lock()
        # guarded_by: self._torrent_lock — decode endpoint -> ConnPool
        self._torrent_pools = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaServer":
        self.endpoint = self._bind_and_accept(
            self.endpoint, f"fleet-replica@{self.endpoint}")
        logger.info("fleet replica %s listening on %s", self.replica_id,
                    self.endpoint)
        if self.router_endpoint:
            self._router_pool = _wire.ConnPool(self.router_endpoint,
                                              max_idle=1)
            self._heartbeat = HeartbeatThread(
                beat=self._beat_router, lease_s=self.lease_s,
                quorum=self.quorum,
                quorum_resource=(f"{self.quorum_member_prefix}"
                                 f"{self.replica_id}"),
                quorum_holder=self.replica_id)
            # synchronous first beat: membership exists before the first
            # request could be routed here
            self._heartbeat.beat_once()
            self._heartbeat.start()
        return self

    def _beat_router(self):
        _wire.call(self._router_pool, "replica_heartbeat", {
            "replica_id": self.replica_id,
            "endpoint": self.endpoint,
            "session": self.session,
            "pulse_port": self.server.pulse_port,
            "lease_s": self.lease_s,
            "role": self.role,
        }, deadline_s=min(self.lease_s, 2.0))

    def kill(self):
        """SIGKILL analog for in-process chaos tests: the RPC front dies
        NOW — no leave, no heartbeat-stop courtesy; the router learns of
        the death the hard way (transport failover + lease expiry),
        which is exactly what the test wants to observe."""
        self._do_stop(leave=False)

    def stop(self):
        """Hard cut of the transport, but a CLEAN membership exit: the
        router is told to leave, so planned shutdowns (deploys, scale-
        down) never cost a failover."""
        self._do_stop(leave=True)

    def _do_stop(self, leave: bool):
        if self._stop.is_set():
            return
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._router_pool is not None:
            if leave:
                try:
                    _wire.call(self._router_pool, "replica_leave",
                               {"replica_id": self.replica_id},
                               deadline_s=1.0)
                except Exception:
                    pass   # lease expiry covers an unreachable router
            self._router_pool.close()
        with self._torrent_lock:
            pools = list(self._torrent_pools.values())
            self._torrent_pools.clear()
        for p in pools:
            p.close()
        self._hard_cut()

    def close(self):
        """Clean shutdown: stop the RPC front, then the serving stack."""
        self.stop()
        self.server.close()

    # -- connection handling (accept/teardown: wire.HardCutServer) ---------

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                msg = _rpc.recv_msg(conn)
            except (ConnectionError, EOFError, OSError):
                return
            if self._stop.is_set():
                return   # a stopped replica behaves like a dead one
            try:
                cmd, payload = msg[0], msg[1]
                meta = msg[2] if len(msg) >= 3 else None
            except (TypeError, IndexError):
                _rpc.send_msg(conn, ("err", "MalformedFrame: expected "
                                     "(cmd, payload[, meta])"))
                continue
            obs = _flags.get_flag("observe")
            wctx = _xray.from_wire(meta) if obs and meta else None
            try:
                if wctx is not None:
                    with _xray.activate(wctx), \
                            _xray.span(f"replica:{cmd}", cat="fleet",
                                       cmd=cmd,
                                       replica=self.replica_id):
                        reply = self._dispatch(cmd, payload)
                else:
                    reply = self._dispatch(cmd, payload)
            except ServeError as e:
                # named + classified: the router re-raises the SAME
                # class and keys failover on its retriable bit
                reply = _wire.serve_error_reply(e)
            except Exception as e:
                reply = ("err", f"{type(e).__name__}: {e}")
            try:
                _rpc.send_msg(conn, reply)
            except (ConnectionError, OSError):
                return
            if cmd == "stop":
                return

    def _dispatch(self, cmd, p):
        handler = getattr(self, f"_h_{cmd}", None)
        if handler is None:
            raise ValueError(f"unknown fleet replica command {cmd!r}")
        return handler(**p)

    # -- request path ------------------------------------------------------

    def _h_infer(self, model, feed, deadline_ms=None):
        fut = self.server.submit(
            model, {k: np.asarray(v) for k, v in feed.items()},
            deadline_ms=deadline_ms)
        # queued-deadline enforcement lives in the batcher; the slack
        # covers a batch already executing when the deadline strikes
        timeout = None if deadline_ms is None else deadline_ms / 1e3 + 30.0
        outs = fut.result(timeout=timeout)
        if self.simulate_device_s:
            with self._device_lock:
                time.sleep(self.simulate_device_s)
        return ("ok", {"outs": [np.asarray(o) for o in outs],
                       "version": getattr(fut, "version_id", None),
                       "version_key": getattr(fut, "version_key", None),
                       "replica_id": self.replica_id})

    def _h_generate(self, model, prompt, max_new_tokens=16,
                    deadline_ms=None):
        res = self.server.generate(model, prompt,
                                   max_new_tokens=max_new_tokens,
                                   deadline_ms=deadline_ms)
        if self.simulate_device_s:
            with self._device_lock:
                time.sleep(self.simulate_device_s)
        ver_key = None
        try:
            cur = self.server.registry.get(model)
            if cur.version_id == res.version_id:
                ver_key = cur.version_key
        except Exception:
            pass
        return ("ok", {"tokens": list(res.tokens),
                       "version": res.version_id,
                       "version_key": ver_key,
                       "ttft_us": res.ttft_us,
                       # engine-observed TTFT rides FleetResult.outs so
                       # fleet callers (torrent_bench's co-located arm)
                       # can compare first-token latency across modes
                       "outs": {"ttft_us": res.ttft_us,
                                "finish_reason": res.finish_reason},
                       "replica_id": self.replica_id})

    # -- fluid-torrent (disaggregated generation halves) -------------------

    def _torrent_pool(self, endpoint: str) -> _wire.ConnPool:
        with self._torrent_lock:
            pool = self._torrent_pools.get(endpoint)
            if pool is None:
                pool = self._torrent_pools[endpoint] = _wire.ConnPool(
                    endpoint, max_idle=2)
            return pool

    def _torrent_admit(self, model, prompt, first_token, kv, max_new,
                       trace):
        """KVStreamReceiver admit hook: inject the wire-delivered
        payload into this replica's decode engine. The kv_begin record's
        trace context (the ORIGINATING routed request) is activated
        around the submit so the decode engine's serve_generate span
        stitches into the same trace as the prefill half."""
        wctx = (_xray.from_wire(trace)
                if _flags.get_flag("observe") and trace else None)
        if wctx is not None:
            with _xray.activate(wctx):
                return self.server.submit_prefilled(
                    model, prompt, first_token, kv,
                    max_new_tokens=max_new)
        return self.server.submit_prefilled(
            model, prompt, first_token, kv, max_new_tokens=max_new)

    def _h_torrent_prefill(self, model, prompt, seq_id, decode_endpoint,
                           max_new_tokens=16, deadline_ms=None):
        """Prefill half: run the prompt here, stream its KV blocks to
        `decode_endpoint`'s `torrent_kv` handler. The router dispatches
        this least-loaded over the prefill pool; a KVTransferError reply
        means the DECODE side is gone — the router re-pins and retries,
        it does not shed this to another prefill replica."""
        trace = None
        if _flags.get_flag("observe") and _xray.current() is not None:
            trace = _xray.to_wire(_xray.current())
        pool = self._torrent_pool(decode_endpoint)

        def send(records):
            value = _wire.call(pool, "torrent_kv", {"records": records},
                               deadline_s=min(
                                   self.lease_s * 2, 10.0))
            return int(value["acked"])

        out = prefill_and_stream(
            self.server, model, prompt, int(max_new_tokens), seq_id,
            send, deadline_ms=deadline_ms, trace=trace)
        # no simulate_device_s sleep here: torrent rehearsals price
        # device time with the serve engine's phase-shaped knobs
        # (simulate_prefill_us_per_token / simulate_decode_step_us),
        # which already ran inside prefill_and_stream — sleeping again
        # under _device_lock would double-charge the prefill
        # the summary rides FleetResult.outs (torrent_prefill is a
        # control reply, not a fetch list)
        return ("ok", {"outs": out, "replica_id": self.replica_id})

    def _h_torrent_kv(self, records):
        """Decode half, transfer plane: apply one record batch, reply
        the contiguous acked watermark (the sender's resume point)."""
        return ("ok", self._kv_recv.handle(records))

    def _h_torrent_collect(self, model, seq_id, deadline_ms=None):
        """Decode half, result plane: block until the injected
        generation finishes, reply its tokens (shaped like generate so
        the router's FleetResult mapping is shared). Collecting releases
        the staging — collect-once semantics."""
        fut = self._kv_recv.future(seq_id)
        timeout = 60.0 if deadline_ms is None else deadline_ms / 1e3 + 30.0
        res = fut.result(timeout=timeout)
        self._kv_recv.release(seq_id)
        ver_key = None
        try:
            cur = self.server.registry.get(model)
            if cur.version_id == res.version_id:
                ver_key = cur.version_key
        except Exception:
            pass
        return ("ok", {"tokens": list(res.tokens),
                       "finish_reason": res.finish_reason,
                       "version": res.version_id,
                       "version_key": ver_key,
                       "ttft_us": res.ttft_us,
                       "replica_id": self.replica_id})

    def _h_torrent_cancel(self, seq_id):
        """Drop a transfer's staging/future (router released the
        session). The generation itself, if already admitted, runs to
        completion on the engine — cancel severs the collect path."""
        self._kv_recv.release(seq_id)
        return ("ok", {"released": True})

    # -- readiness / stats -------------------------------------------------

    def readiness(self) -> dict:
        """The per-model verdict, shaped like the pulse /readyz check's
        detail — one fact set whichever transport polls it."""
        ok, detail = self.server._pulse_queue_check()
        return {"status": "ok" if ok else "unready",
                "replica_id": self.replica_id,
                "session": self.session,
                "models": detail,
                "role": self.role,
                "pulse_port": self.server.pulse_port}

    def _h_readyz(self):
        return ("ok", self.readiness())

    def _h_ping(self):
        return ("ok", {"replica_id": self.replica_id,
                       "session": self.session})

    def _h_fleet_stats(self):
        sparse = {}
        for name in self.server.registry.names():
            try:
                plan = self.server.registry.get(name).sparse_plan
            except Exception:
                continue
            if plan is not None:
                sparse[name] = plan.stats()
        return ("ok", {
            "replica_id": self.replica_id,
            "stats": self.server.stats(),
            "sparse": sparse,
            # the cross-process observatory gate: a fleet drill sums
            # this over every replica and requires ZERO growth after
            # warmup — steady-state recompiles anywhere fail the fleet
            "unexpected_recompiles":
                len(_steplog.observatory().unexpected()),
        })

    # -- coordinated swap --------------------------------------------------

    def _h_prepare_swap(self, model, dirname=None):
        ver = self.server.prepare_swap(model, dirname)
        return ("ok", {"version": ver.version_id,
                       "version_key": ver.version_key,
                       "warmed": bool(ver.warmed)})

    def _h_commit_swap(self, model):
        ver = self.server.commit_swap(model)
        return ("ok", {"version": ver.version_id,
                       "version_key": ver.version_key})

    def _h_abort_swap(self, model):
        return ("ok", {"aborted": self.server.abort_swap(model)})

    def _h_stop(self):
        # reply first (the dispatcher sends, then the conn thread exits),
        # then die hard on a helper thread so the caller gets its ack
        threading.Thread(target=self.stop, daemon=True).start()
        return ("ok", None)
