"""Re-export of program-level autodiff (reference: fluid.backward)."""

from .core.backward import append_backward, calc_gradient  # noqa: F401
