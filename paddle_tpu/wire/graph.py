"""In-graph gradient quantization for the GSPMD (collective) path.

EQuARX (PAPERS.md) quantizes the AllReduce inside XLA; that pass is not
reachable from outside the compiler, so fluid-wire realizes the same
numerics at the IR level: a `comm_quant_dequant` op is inserted between
each gradient and its optimizer op, quantize-dequantizing the gradient
with the abs-max idiom of `ops/quantize.py` — per-chunk int8 scales or a
bf16 round — plus PERSISTENT error feedback (the residual var rides the
program state like an optimizer accumulator, so quantization noise
cancels across steps instead of accumulating).

Because the op is ordinary IR, the GSPMD lowering stays ONE jitted
program: the compile cache sees one steady-state executable (zero
recompiles, observatory-verified in tests/test_wire.py), and the
residual state is donated/updated in place like every other persistable.

Scope honesty: this is a QDQ (fake-quant) pass. The op emits float32
grid-valued gradients, so full-precision bytes still cross the
all-reduce today — what it delivers is the quantized collective's
NUMERICS (int8/bf16 grid + error feedback, convergence pinned against
the unquantized run) inside one jitted program, plus the IR boundary a
true quantized-collective lowering can later slot into without touching
user programs. The measured on-wire BYTE reduction of fluid-wire lives
on the pserver RPC path (wire/codec.py; BENCH `wire_compression_x`).

Threaded through two surfaces:

    DistributeTranspilerConfig.comm_quant = "int8"   # sync collective /
                                                     # hybrid dense path
    BuildStrategy.comm_quant = "bf16"                # ParallelExecutor

Both call `apply_comm_quant` below.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..core import ir
from ..core.registry import register_op
from .codec import _INT8_BINS, CODECS, DEFAULT_CHUNK, WireCodecError

RESIDUAL_SUFFIX = "@COMM_RES"
QUANT_SUFFIX = "@COMM_QUANT"


@register_op("comm_quant_dequant", propagate_seqlen=False)
def _comm_quant_dequant(ctx, Grad, Residual):
    """Out = dequant(quant(Grad + Residual)); ResidualOut carries the new
    quantization error. Mirrors wire/codec.py's host math exactly (the
    int8 per-chunk abs-max scale and the bf16 round-to-nearest-even), so
    the in-graph and host paths share one numerical contract."""
    codec = ctx.attr("codec", "int8")
    comp = Grad + Residual
    if codec == "bf16":
        deq = comp.astype(jnp.bfloat16).astype(comp.dtype)
    elif codec == "int8":
        chunk = max(int(ctx.attr("chunk", DEFAULT_CHUNK)), 1)
        shape = comp.shape
        flat = comp.reshape(-1)
        n = flat.shape[0]
        pad = (-n) % chunk
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), dtype=comp.dtype)])
        x = flat.reshape(-1, chunk)
        scale = jnp.max(jnp.abs(x), axis=1) / _INT8_BINS
        safe = jnp.where(scale > 0, scale, 1.0).astype(x.dtype)
        q = jnp.round(jnp.clip(x / safe[:, None], -_INT8_BINS, _INT8_BINS))
        deq = (q * safe[:, None]).reshape(-1)[:n].reshape(shape)
    else:
        raise WireCodecError(
            f"comm_quant_dequant: unknown codec {codec!r}; known "
            f"in-graph codecs: ('int8', 'bf16')")
    return {"Out": deq, "ResidualOut": comp - deq}


def _optimizer_op_types():
    # deferred import: transpiler imports this module to apply the pass
    from ..transpiler.distribute_transpiler import OPTIMIZE_OP_TYPES
    return OPTIMIZE_OP_TYPES


def apply_comm_quant(program: ir.Program, codec: str = "int8",
                     chunk: int = DEFAULT_CHUNK,
                     startup_program: Optional[ir.Program] = None,
                     scope=None) -> List[str]:
    """Rewrite `program` so every dense optimizer op consumes a
    quantize-dequantized gradient with persistent error feedback.

    For each optimizer op in the global block: a persistable residual
    var `<grad>@COMM_RES` (zeros, param-shaped) is created, a
    `comm_quant_dequant` op is inserted just before the optimizer op,
    and the optimizer's Grad input is rewired to `<grad>@COMM_QUANT`.
    Idempotent: already-rewired optimizer ops are skipped.

    The residual must be materialized before the first step:
    `startup_program` (when given) gains a `fill_constant` zero-init per
    residual, and/or `scope` (when given — the ParallelExecutor surface,
    whose startup typically already ran) gets the zeros written directly.

    Returns the list of rewired parameter names.
    """
    if codec in (None, "raw"):
        return []
    if codec not in CODECS or codec == "raw":
        raise WireCodecError(
            f"comm_quant codec must be one of ('int8', 'bf16'), got "
            f"{codec!r}")
    block = program.global_block()
    opt_types = _optimizer_op_types()
    sites = []   # (op index, optimizer op)
    for i, op in enumerate(block.ops):
        if op.type not in opt_types:
            continue
        grads = op.input("Grad")
        if not grads or grads[0].endswith(QUANT_SUFFIX):
            continue   # no grad slot / already rewired
        sites.append((i, op))

    rewired: List[str] = []
    skipped: List[str] = []
    # insert back-to-front so earlier indices stay valid
    for i, op in reversed(sites):
        gname = op.input("Grad")[0]
        pname = op.input("Param")[0]
        pvar = block._find_var_recursive(pname)
        if pvar is None or not pvar.shape or any(d == -1 for d in pvar.shape):
            skipped.append(pname)   # no static shape for the residual
            continue
        shape, dtype = tuple(pvar.shape), pvar.dtype
        res_name = gname + RESIDUAL_SUFFIX
        q_name = gname + QUANT_SUFFIX
        if not block.has_var(res_name):
            block.create_var(name=res_name, shape=shape, dtype=dtype,
                             persistable=True)
        if not block.has_var(q_name):
            block.create_var(name=q_name, shape=shape, dtype=dtype)
        block.insert_op(
            i, "comm_quant_dequant",
            inputs={"Grad": [gname], "Residual": [res_name]},
            outputs={"Out": [q_name], "ResidualOut": [res_name]},
            attrs={"codec": codec, "chunk": int(chunk),
                   "__role__": "optimize"})
        op.inputs["Grad"] = [q_name]
        rewired.append(pname)
        if startup_program is not None:
            sblock = startup_program.global_block()
            if not sblock.has_var(res_name):
                sblock.create_var(name=res_name, shape=shape, dtype=dtype,
                                  persistable=True)
                sblock.append_op(
                    "fill_constant", outputs={"Out": [res_name]},
                    attrs={"shape": list(shape), "dtype": dtype,
                           "value": 0.0})
        if scope is not None and scope.find_var(res_name) is None:
            scope.set_var(res_name, np.zeros(shape, dtype=dtype))
    if rewired:
        program._bump()
    already = any(
        op.type in opt_types and op.input("Grad")
        and op.input("Grad")[0].endswith(QUANT_SUFFIX)
        for op in block.ops)
    if skipped or not (rewired or already):
        # a requested-but-inactive quantizer must not be silent: the user
        # believes gradients quantize while they travel full-precision
        import warnings
        what = (f"params without static shapes skipped: "
                f"{sorted(skipped)}" if skipped
                else "no dense optimizer op with a gradient found")
        scope_word = "partially" if (rewired or already) else "entirely"
        warnings.warn(
            f"comm_quant={codec!r} is {scope_word} inactive — {what}; "
            f"the affected gradients stay full-precision",
            RuntimeWarning, stacklevel=2)
    return list(reversed(rewired))
