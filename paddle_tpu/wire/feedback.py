"""Per-tensor error feedback for quantized gradient communication.

The EF-SGD idea (and EQuARX's quality story): quantization error is not
discarded, it is CARRIED — the residual `r_t = g_t' - Q(g_t')` (where
`g_t' = g_t + r_{t-1}`) is added into the next step's gradient before
encoding, so quantization noise cancels over steps instead of
accumulating into a bias. With abs-max int8 this keeps sync-PS training
inside the unquantized loss band (pinned by tests/test_wire.py).

Replay safety contract (the `quant_flaky_rpc` chaos drill): the residual
is updated ONCE per logical push, only after the frame is known
delivered. `encode()` returns `(payload, commit)` — the caller invokes
`commit()` after its RPC succeeds. Transport-level retries resend the
SAME already-encoded bytes; a caller-level retry after a failed call
re-encodes from the UNCHANGED residual and produces bit-identical bytes
(the gradient and residual are both unchanged), so a frame that was
secretly applied server-side is deduplicated by the batch-id watermark
and the residual is never double-applied.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from . import codec as _codec


class ErrorFeedback:
    """Residual store keyed by an opaque key (the client uses
    (endpoint, var name) so replica/endpoint moves never mix streams)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._residual: Dict[Any, np.ndarray] = {}
        self._committed: Dict[Any, Any] = {}   # key -> last committed tag

    def encode(self, key, arr, codec: str, name: str = "<tensor>",
               chunk: int = _codec.DEFAULT_CHUNK, tag: Any = None
               ) -> Tuple[Any, Callable[[], None]]:
        """Encode `arr + residual[key]`; returns (payload, commit).
        `commit()` stores the new residual — call it only once the frame
        was delivered (see the module docstring's replay contract).

        `tag` identifies the LOGICAL push (the sync path passes
        (session, batch_id)): committing the same tag twice is a no-op.
        This closes the caller-level-retry window — a batch whose push
        landed and committed but whose barrier reply was lost gets
        re-pushed by the retrying caller; the server deduplicates the
        frame by batch id, and the dedup here keeps the retry's
        never-applied quantization error out of the residual stream."""
        arr = np.asarray(arr, dtype=np.float32)
        with self._lock:
            r = self._residual.get(key)
        compensated = arr + r if r is not None else arr
        payload, deq = _codec.encode_with_dequant(compensated, codec,
                                                  name=name, chunk=chunk)
        new_r = compensated - deq if _codec.is_encoded(payload) else None

        def commit():
            if new_r is None:
                return
            with self._lock:
                if tag is not None and self._committed.get(key) == tag:
                    return   # replay of an already-committed logical push
                self._residual[key] = new_r
                if tag is not None:
                    self._committed[key] = tag

        return payload, commit

    def residual(self, key) -> Optional[np.ndarray]:
        with self._lock:
            return self._residual.get(key)

    # -- checkpoint integration (ark bit-identical resume) -----------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        """Residual streams as flat npz-compatible arrays, keyed
        `"<endpoint>|<name>"`. The residual is TRAINER-LOCAL state: a
        resume that restores params/slots/RNG but not the residuals
        produces pushes that differ from the uninterrupted run by up to
        one quantum per tensor — quality-neutral (error feedback is
        noise cancellation, not correctness), but not bit-identical.
        Callers that need ark's bit-identical-resume guarantee under
        `comm_quant` merge this into the checkpoint `arrays` and feed it
        back through `load_state_dict` after restore (the commit-tag
        dedup window is per-process and deliberately NOT serialized: a
        resumed process re-pushes its batch from scratch)."""
        with self._lock:
            return {f"{ep}|{name}": r.copy()
                    for (ep, name), r in self._residual.items()}

    def load_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        with self._lock:
            for flat, r in state.items():
                ep, _, name = flat.partition("|")
                self._residual[(ep, name)] = np.asarray(r, np.float32)

    def clear(self):
        with self._lock:
            self._residual.clear()
            self._committed.clear()
