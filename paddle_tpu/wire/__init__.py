"""fluid-wire: quantized + compressed communication for distributed
training (gradient AllReduce and parameter-server wire traffic).

Grounded in EQuARX (PAPERS.md — quantized AllReduce in XLA, ~2x
collective speedup at negligible quality loss) and the TF system paper's
compressed parameter-server traffic. Two prongs, one numerical contract
(docs/COMMUNICATION.md):

- **Host wire codecs** (`wire.codec`, `wire.feedback`): float32 tensors
  travel the pserver RPC as codec-tagged payloads — per-chunk abs-max
  int8 (~4x) or bf16 (2x) — with per-tensor client-side error feedback
  on gradient pushes. Raw stays the default; clients negotiate the codec
  per endpoint (`wire_caps`) and degrade to raw against legacy servers.
  Select with `PSClient(comm_quant="int8")` or
  `DistributeTranspilerConfig.comm_quant`.

- **In-graph gradient quantization** (`wire.graph`): a
  `comm_quant_dequant` op (abs-max idiom of ops/quantize.py + persistent
  error-feedback residual) inserted before each optimizer op, so the
  GSPMD lowering stays one jitted program and each dp shard quantizes
  its gradient contribution at the collective boundary. Select with
  `BuildStrategy.comm_quant` or `DistributeTranspilerConfig.comm_quant`.

Compression is a first-class metric: `pserver_wire_bytes_raw` /
`pserver_wire_bytes_encoded` counters per command (surfaced by
`tools/telemetry_dump.py --format table` and bench.py's `wire` segment).
"""

from __future__ import annotations

from typing import List

from . import codec, feedback, graph  # noqa: F401  (graph registers the op)
from .codec import (CODECS, DEFAULT_CHUNK, NonFiniteTensorError,  # noqa: F401
                    WireCodecError, compression_ratio, decode_tensor,
                    encode_tensor, encode_with_dequant, is_encoded,
                    maybe_decode, payload_nbytes)
from .feedback import ErrorFeedback  # noqa: F401
from .graph import apply_comm_quant  # noqa: F401

# counters shared by client/server/tools (one place to get the names right)
RAW_BYTES_METRIC = "pserver_wire_bytes_raw"
ENCODED_BYTES_METRIC = "pserver_wire_bytes_encoded"


def wire_table(registry=None) -> List[str]:
    """Human-readable per-command compression table from the metrics
    registry (what `tools/telemetry_dump.py --format table` prints).
    Empty when no wire traffic was recorded."""
    if registry is None:
        from ..observe import metrics as _metrics
        registry = _metrics.default_registry()
    raw = registry.get(RAW_BYTES_METRIC)
    enc = registry.get(ENCODED_BYTES_METRIC)
    if raw is None or enc is None:
        return []
    rows = []
    for labels, r in sorted(raw.items(), key=lambda kv: str(kv[0])):
        rows.append((labels.get("cmd", "?"), r, enc.value(**labels)))
    return _wire_lines(rows)


def wire_table_from_snapshot(snapshot) -> List[str]:
    """Same table from a registry SNAPSHOT dict (fluid-pulse: what a
    live `/status` scrape carries), so `tools/telemetry_dump.py --url`
    prints the identical table for a remote process."""
    raw = (snapshot.get(RAW_BYTES_METRIC) or {}).get("values") or {}
    enc = (snapshot.get(ENCODED_BYTES_METRIC) or {}).get("values") or {}
    if not raw or not enc:
        return []
    rows = []
    for labelstr, r in sorted(raw.items()):
        labels = dict(p.split("=", 1) for p in labelstr.split(",")
                      if "=" in p)
        rows.append((labels.get("cmd", "?"), r, enc.get(labelstr, 0.0)))
    return _wire_lines(rows)


def _wire_lines(rows) -> List[str]:
    lines = []
    total_raw = total_enc = 0.0
    for cmd, r, e in rows:
        total_raw += r
        total_enc += e
        lines.append(f"  {cmd:<20} {r:>14,.0f} -> {e:>14,.0f} bytes  "
                     f"({compression_ratio(r, e):.2f}x)")
    if lines:
        lines.insert(0, "wire bytes (raw -> on-wire, per command):")
        lines.append(f"  {'TOTAL':<20} {total_raw:>14,.0f} -> "
                     f"{total_enc:>14,.0f} bytes  "
                     f"({compression_ratio(total_raw, total_enc):.2f}x)")
    return lines
