"""Host-side wire codecs for the communication-compression layer.

Reference analog: the reference pserver assumed compressed wire traffic
(the TF system paper's parameter-server story); EQuARX (PAPERS.md) shows
quantized collectives deliver ~2x at negligible quality loss. This module
is the HOST half of fluid-wire: numpy codecs that turn a float32 tensor
into a compact tagged payload riding the existing length-prefixed pickle
frames of `pserver/rpc.py` — the rpc layer itself is codec-agnostic, it
just moves whatever the payload dict holds.

Wire format ("codec-tagged payload"): an encoded tensor travels as a
plain dict

    {"__wire__": 1, "codec": "int8", "shape": [...], "dtype": "float32",
     "chunk": 2048, "scale": float32[n_chunks], "data": int8[n]}

(bf16 drops chunk/scale and carries uint16 mantissa-rounded halves).
Every field is a container, str, int, or numpy array — exactly what the
restricted unpickler already admits, so no new trust surface. A RAW
tensor stays a bare ndarray (the legacy payload, byte-identical to
pre-wire traffic): servers tell the two apart with `is_encoded`, so a
legacy peer that never sends tagged payloads interoperates unchanged —
the same compatibility posture as the xray 2-tuple/3-tuple frame.

Codecs:

    raw   — identity (ndarray passthrough), the default
    bf16  — round-to-nearest-even truncation to bfloat16 (2.0x)
    int8  — per-chunk abs-max scaling to int8 (~3.97x at chunk 2048)

Error handling is LOUD by contract: a non-finite tensor refuses to
encode with `NonFiniteTensorError` naming the tensor (quantizing an inf
would silently saturate every element of its chunk), and a float64
tensor refuses with `WireCodecError` (the comm boundary is float32 —
the `comm-float64` lint enforces the same contract statically on the
in-graph path).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

WIRE_TAG = "__wire__"
WIRE_VERSION = 1
CODECS = ("raw", "bf16", "int8")
DEFAULT_CHUNK = 2048
# int8 symmetric range: +-127 (not -128: abs-max scaling is symmetric,
# matching the reference fake_quantize abs_max bin count (1<<7)-1)
_INT8_BINS = 127.0


class WireCodecError(ValueError):
    """The tensor cannot travel through the requested codec (wrong dtype,
    unknown codec, malformed payload)."""


class NonFiniteTensorError(WireCodecError):
    """The tensor holds inf/nan: quantizing it would silently saturate
    the whole chunk, so the encode refuses, naming the tensor."""


def _check_encodable(arr: np.ndarray, codec: str, name: str) -> np.ndarray:
    if codec not in CODECS:
        raise WireCodecError(
            f"unknown wire codec {codec!r} for {name!r}; known: {CODECS}")
    arr = np.asarray(arr)
    if arr.dtype != np.float32:
        raise WireCodecError(
            f"wire codec {codec!r} encodes float32 tensors only; {name!r} "
            f"is {arr.dtype} — the communication boundary is float32 "
            f"(see the comm-float64 lint for the in-graph contract)")
    if arr.size and not np.isfinite(arr).all():
        raise NonFiniteTensorError(
            f"tensor {name!r} holds inf/nan values — refusing to quantize "
            f"(an inf abs-max would saturate its whole chunk to zero "
            f"information); fix the producing step or clip first")
    return arr


def _bf16_round(arr: np.ndarray) -> np.ndarray:
    """f32 -> uint16 bfloat16 halves, round-to-nearest-even."""
    u = arr.ravel().view(np.uint32)
    rounded = (u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1))
               ) >> np.uint32(16)
    return rounded.astype(np.uint16)


def _bf16_expand(data: np.ndarray) -> np.ndarray:
    return (data.astype(np.uint32) << np.uint32(16)).view(np.float32)


def _int8_scales(arr_flat: np.ndarray, chunk: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(padded [n_chunks, chunk] view, per-chunk scale). scale is
    abs-max/127, clamped so an all-zero chunk divides by 1 (and decodes
    to exact zeros)."""
    n = arr_flat.size
    pad = (-n) % chunk
    if pad:
        arr_flat = np.concatenate(
            [arr_flat, np.zeros(pad, dtype=arr_flat.dtype)])
    x = arr_flat.reshape(-1, chunk)
    scale = (np.abs(x).max(axis=1) / np.float32(_INT8_BINS)).astype(
        np.float32)
    safe = np.where(scale > 0, scale, np.float32(1.0)).astype(np.float32)
    return x, safe


def _encode(arr: np.ndarray, codec: str, name: str, chunk: int,
            with_deq: bool):
    """Shared encode core: (payload, dequantized-or-None). The dequant
    reuses the q/scale arrays already in hand, so error feedback never
    pays a second decode pass over the frame it just built."""
    arr = _check_encodable(arr, codec, name)
    if codec == "bf16":
        data = _bf16_round(arr)
        payload = {WIRE_TAG: WIRE_VERSION, "codec": "bf16",
                   "shape": list(arr.shape), "dtype": "float32",
                   "data": data}
        deq = _bf16_expand(data).reshape(arr.shape) if with_deq else None
        return payload, deq
    # int8, per-chunk abs-max scale
    chunk = max(int(chunk), 1)
    x, safe = _int8_scales(arr.ravel(), chunk)
    q = np.rint(np.clip(x / safe[:, None], -_INT8_BINS, _INT8_BINS)
                ).astype(np.int8)
    payload = {WIRE_TAG: WIRE_VERSION, "codec": "int8",
               "shape": list(arr.shape), "dtype": "float32",
               "chunk": chunk, "scale": safe,
               "data": q.ravel()[: arr.size]}
    deq = None
    if with_deq:
        deq = (q.astype(np.float32) * safe[:, None]
               ).ravel()[: arr.size].reshape(arr.shape)
    return payload, deq


def encode_tensor(arr: Any, codec: str, name: str = "<tensor>",
                  chunk: int = DEFAULT_CHUNK):
    """Encode one tensor. Returns the tagged payload dict — or, for
    codec "raw", the bare ndarray (the legacy wire shape, so a raw
    client's bytes are bit-identical to pre-wire traffic)."""
    if codec == "raw" or codec is None:
        return np.asarray(arr)
    return _encode(arr, codec, name, chunk, with_deq=False)[0]


def encode_with_dequant(arr: Any, codec: str, name: str = "<tensor>",
                        chunk: int = DEFAULT_CHUNK):
    """(payload, dequantized f32 array): what `decode_tensor(payload)`
    would return, computed from the encoder's own q/scale arrays —
    bit-identical to the decode (test-pinned), without a second pass.
    For "raw" the payload IS the array and the dequant is the array."""
    if codec == "raw" or codec is None:
        a = np.asarray(arr)
        return a, a
    return _encode(arr, codec, name, chunk, with_deq=True)


def is_encoded(obj: Any) -> bool:
    return isinstance(obj, dict) and WIRE_TAG in obj


def decode_tensor(payload: Dict[str, Any]) -> np.ndarray:
    """Tagged payload -> float32 ndarray. Malformed payloads raise
    WireCodecError naming what is wrong (a corrupt frame must surface as
    a diagnosable error reply, never a half-decoded tensor)."""
    try:
        codec = payload["codec"]
        shape = tuple(int(d) for d in payload["shape"])
        data = np.asarray(payload["data"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireCodecError(f"malformed wire payload: {e!r}") from e
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if codec == "bf16":
        if data.dtype != np.uint16 or data.size != n:
            raise WireCodecError(
                f"bf16 payload holds {data.size} x {data.dtype}, expected "
                f"{n} x uint16 for shape {shape}")
        return _bf16_expand(data).reshape(shape)
    if codec == "int8":
        chunk = int(payload.get("chunk", DEFAULT_CHUNK))
        if chunk < 1:
            raise WireCodecError(
                f"int8 payload chunk is {chunk}, expected >= 1")
        scale = np.asarray(payload.get("scale"))
        if data.dtype != np.int8 or data.size != n:
            raise WireCodecError(
                f"int8 payload holds {data.size} x {data.dtype}, expected "
                f"{n} x int8 for shape {shape}")
        if payload.get("scale") is None or scale.ndim != 1 \
                or scale.dtype.kind != "f":
            raise WireCodecError(
                f"int8 payload scale is "
                f"{scale.dtype if payload.get('scale') is not None else None}"
                f" (ndim {scale.ndim}), expected a 1-d float array of "
                f"per-chunk scales")
        n_chunks = (n + chunk - 1) // chunk if n else 0
        if scale.size != n_chunks:
            raise WireCodecError(
                f"int8 payload carries {scale.size} chunk scales, "
                f"expected {n_chunks} (chunk={chunk}, n={n})")
        if not n:
            return np.zeros(shape, np.float32)
        # O(n) dequant: per-element scales via repeat with a short final
        # chunk — the padded tail is never materialized, so a corrupt
        # frame advertising a huge `chunk` cannot force a chunk-sized
        # allocation (it decodes in O(data) or fails the checks above)
        counts = np.full(n_chunks, chunk, dtype=np.int64)
        counts[-1] = n - chunk * (n_chunks - 1)
        out = data.astype(np.float32) * np.repeat(
            scale.astype(np.float32), counts)
        return out.reshape(shape)
    raise WireCodecError(f"unknown wire codec {codec!r} in payload")


def maybe_decode(obj: Any) -> np.ndarray:
    """Server-side entry: decode a tagged payload, pass a raw array
    through — the one call that makes every handler legacy-compatible."""
    if is_encoded(obj):
        return decode_tensor(obj)
    return np.asarray(obj)


def payload_nbytes(obj: Any) -> int:
    """On-wire tensor bytes of a payload (data + scales for encoded
    payloads, nbytes for raw arrays) — what the wire byte counters
    record. Framing/pickle overhead is excluded on both sides of the
    raw/encoded comparison, so the ratio is the codec's own."""
    if is_encoded(obj):
        total = 0
        for k in ("data", "scale"):
            v = obj.get(k)
            if v is not None:
                total += np.asarray(v).nbytes
        return total
    return np.asarray(obj).nbytes


def compression_ratio(raw_nbytes: float, encoded_nbytes: float) -> float:
    return raw_nbytes / encoded_nbytes if encoded_nbytes else 0.0
