"""Standalone single-op construction + execution (reference
python/paddle/fluid/op.py: OperatorFactory / `Operator` — the low-level
handle the reference's OpTest unit tests drive ops with).

TPU-native redesign: instead of building a C++ OpDesc and dispatching a
kernel, the returned Operator binds scope variable names to the op's
lowering rule and `run(scope, place)` executes it eagerly through jax —
the same rule the compiled whole-program path uses, so a value checked
here is the value the fused step computes."""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .core import registry


class Operator:
    """`Operator("scale", X="x", Out="y", scale=2.0)`; slots bind scope
    var NAMES (a list for multi-var slots), everything else is an attr.
    `run(scope, place)` reads inputs from the scope, applies the lowering
    rule, and writes the outputs back (reference op.py usage)."""

    def __init__(self, type, **kwargs):
        if not registry.is_registered(type):
            raise ValueError(f"The operator: {type} is not registered.")
        self.type = type
        opdef = registry.get_op_def(type)
        in_slots = set(opdef.input_slots)
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        self.attrs: Dict[str, object] = {}
        for key, val in kwargs.items():
            if key in in_slots:
                self.inputs[key] = list(val) if isinstance(
                    val, (list, tuple)) else [val]
            elif key[:1].isupper():
                # capitalized non-input slot = output name binding (the
                # reference resolves against the op proto's output list;
                # the lowering registry discovers outputs at run time)
                self.outputs[key] = list(val) if isinstance(
                    val, (list, tuple)) else [val]
            else:
                self.attrs[key] = val

    def input_names(self):
        return list(self.inputs)

    def output_names(self):
        return list(self.outputs)

    def run(self, scope, place=None):
        import jax

        opdef = registry.get_op_def(self.type)
        ins = {}
        for slot, names in self.inputs.items():
            vals = []
            for n in names:
                v = scope.find_var(n)
                if v is None:
                    raise KeyError(f"op {self.type}: input var {n!r} not "
                                   f"found in scope")
                vals.append(v)
            ins[slot] = vals
        ctx = registry.LoweringContext(self.attrs, key=jax.random.key(0))
        outs = registry.call_rule(opdef, ctx, ins)
        for slot, names in self.outputs.items():
            produced = outs.get(slot)
            if produced is None:
                continue
            vals = produced if isinstance(produced, (list, tuple)) \
                else [produced]
            if len(vals) != len(names):
                raise ValueError(
                    f"op {self.type}: slot {slot} produced {len(vals)} "
                    f"value(s) but {len(names)} name(s) were bound")
            for name, val in zip(names, vals):
                scope.set_var(name, np.asarray(val))
        return outs
