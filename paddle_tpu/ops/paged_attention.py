"""fluid-decode: ragged paged attention over a block-allocated KV cache.

Autoregressive decode is memory-bound: every generated token re-reads the
whole K/V history. Keeping that history contiguous per sequence would
force either per-length compile signatures (a recompile per token) or a
[slots, max_context] dense cache whose padding is re-read every step.
The paged layout (Ragged Paged Attention, PAPERS.md) fixes both at once:

- K/V live in fixed-size BLOCKS ``[num_blocks, block_size, heads, dh]``
  owned by a persistent scope var, so every decode step has ONE static
  shape signature and the compile cache stays warm forever;
- each sequence owns an ordered list of block ids (its BLOCK TABLE, fed
  as a ``[slots, max_blocks_per_seq]`` int32 array); attention gathers
  K/V through the table and masks lanes at or past the sequence length,
  so wildly ragged sequences share one step;
- block 0 is a reserved TRASH block: inactive slots (and the padding
  lanes of prefill writes) scatter there, keeping every scatter static —
  no lane is ever conditionally skipped, just redirected somewhere no
  read can see (reads mask by position, and position >= seq_len lanes
  are masked regardless of which block the table names).

Two phases share the cache:

- ``prefill_attention``: the prompt runs ordinary causal (flash)
  attention at its bucket-ladder rung, and its per-position K/V are
  scattered into the sequence's blocks in the same jitted step;
- ``paged_attention``: one new token per occupied slot — append its K/V
  at position ``seq_len - 1``, attend over ``[0, seq_len)`` through the
  block table.

On TPU (or under PADDLE_TPU_PALLAS_INTERPRET=1) the decode read side
runs as a Pallas kernel streaming cache blocks through the grid's
innermost dimension with the block-table indirection in the index map
(scalar prefetch); everywhere else a masked-lane jnp reference computes
the same math — tests pin the reference path bit-identical to dense
attention on the valid region, and the kernel against the reference
under the interpreter.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from .pallas_attention import NEG_INF, flash_attention


def _interpret():
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


def _pallas_ok():
    return jax.default_backend() != "cpu" or _interpret()


# ---------------------------------------------------------------------------
# cache scatter (append / prefill write)
# ---------------------------------------------------------------------------

def kv_cache_append(k_cache, v_cache, k_new, v_new, block_tables, seq_lens):
    """Write one new token's K/V per slot at position ``seq_len - 1``.

    ``k_new``/``v_new``: [S, H, Dh]; caches [NB, BS, H, Dh]. Inactive
    slots (seq_len == 0) write into the trash block 0 — the scatter stays
    static and nothing ever reads block 0 unmasked."""
    bs = k_cache.shape[1]
    pos = jnp.maximum(seq_lens - 1, 0)
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    active = seq_lens > 0
    blk = jnp.where(active, blk, 0)
    off = jnp.where(active, pos % bs, 0)
    k_cache = k_cache.at[blk, off].set(k_new.astype(k_cache.dtype))
    v_cache = v_cache.at[blk, off].set(v_new.astype(v_cache.dtype))
    return k_cache, v_cache


def kv_cache_prefill_write(k_cache, v_cache, k, v, block_tables, seq_lens):
    """Scatter a padded prompt's K/V ([B, T, H, Dh]) into each row's
    blocks; positions at or past the row's seq_len land in trash block 0."""
    bs = k_cache.shape[1]
    B, T = k.shape[0], k.shape[1]
    t = jnp.arange(T)
    blk = jnp.take_along_axis(
        block_tables, jnp.broadcast_to((t // bs)[None, :], (B, T)), axis=1)
    valid = t[None, :] < seq_lens[:, None]
    blk = jnp.where(valid, blk, 0)
    off = jnp.broadcast_to((t % bs)[None, :], (B, T))
    flat_blk = blk.reshape(-1)
    flat_off = off.reshape(-1)
    k_cache = k_cache.at[flat_blk, flat_off].set(
        k.reshape((B * T,) + k.shape[2:]).astype(k_cache.dtype))
    v_cache = v_cache.at[flat_blk, flat_off].set(
        v.reshape((B * T,) + v.shape[2:]).astype(v_cache.dtype))
    return k_cache, v_cache


# ---------------------------------------------------------------------------
# masked-lane reference math (CPU path; the numerical contract)
# ---------------------------------------------------------------------------

def paged_attention_reference(q, k_cache, v_cache, block_tables, seq_lens,
                              sm_scale):
    """q: [S, H, Dh] (one token per slot). Gathers each slot's K/V
    through its block table into a dense [S, T, H, Dh] view (T =
    max_blocks_per_seq * block_size), masks lanes >= seq_len, and runs
    one softmax(QK^T)V. Inactive slots return zeros."""
    S, H, Dh = q.shape
    nb, bs = k_cache.shape[0], k_cache.shape[1]
    T = block_tables.shape[1] * bs
    flat = (block_tables[:, :, None] * bs
            + jnp.arange(bs)[None, None, :]).reshape(S, T)
    k = jnp.take(k_cache.reshape(nb * bs, H, Dh), flat, axis=0)
    v = jnp.take(v_cache.reshape(nb * bs, H, Dh), flat, axis=0)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    mask = jnp.arange(T)[None, :] < seq_lens[:, None]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("sht,sthd->shd", p, v.astype(jnp.float32)) \
        / jnp.maximum(l, 1e-20)[..., 0][..., None]
    o = jnp.where((seq_lens > 0)[:, None, None], o, 0.0)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# pallas kernel: stream cache blocks via block-table indirection
# ---------------------------------------------------------------------------

def _paged_decode_kernel(seq_lens_ref, bt_ref, q_ref, k_ref, v_ref, o_ref,
                         m_sc, l_sc, acc_sc, *, sm_scale, block_size):
    """Grid (slot, block-ordinal). The k/v BlockSpec index maps read the
    prefetched block table, so program (s, j) sees the j-th cache block
    of slot s — the paged gather costs a scalar lookup, not a host-side
    reorder. Online-softmax state is carried in VMEM scratch across the
    innermost (sequential) dimension, exactly the flash-attention idiom
    of ops/pallas_attention.py."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    seq_len = seq_lens_ref[s]
    # blocks wholly past the sequence contribute nothing; an inactive
    # slot (seq_len 0) never updates, leaving acc at zeros
    live = j * block_size < seq_len

    @pl.when(live)
    def _update():
        q = q_ref[0]                                    # [H, Dh]
        k = k_ref[0]                                    # [BS, H, Dh]
        v = v_ref[0]
        scores = jnp.einsum(
            "hd,bhd->hb", q.astype(jnp.float32),
            k.astype(jnp.float32),
            preferred_element_type=jnp.float32) * sm_scale  # [H, BS]
        pos = j * block_size + lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(pos < seq_len, scores, NEG_INF)
        m = m_sc[...]
        m_new = jnp.maximum(m, jnp.max(scores, axis=1))
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_sc[...] = l_sc[...] * alpha + jnp.sum(p, axis=1)
        acc_sc[...] = acc_sc[...] * alpha[:, None] + jnp.einsum(
            "hb,bhd->hd", p, v.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m_sc[...] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.maximum(l_sc[...], 1e-20)
        o_ref[0] = (acc_sc[...] / l[:, None]).astype(o_ref.dtype)


def _paged_attention_pallas(q, k_cache, v_cache, block_tables, seq_lens,
                            sm_scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, Dh = q.shape
    bs = k_cache.shape[1]
    max_b = block_tables.shape[1]
    kernel = functools.partial(_paged_decode_kernel, sm_scale=sm_scale,
                               block_size=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, max_b),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda s, j, sl, bt: (s, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh),
                         lambda s, j, sl, bt: (bt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh),
                         lambda s, j, sl, bt: (bt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda s, j, sl, bt: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H,), jnp.float32),
            pltpu.VMEM((H,), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, Dh), q.dtype),
        interpret=_interpret(),
    )(seq_lens.astype(jnp.int32), block_tables.astype(jnp.int32),
      q, k_cache, v_cache)


def paged_attention(q, k_cache, v_cache, block_tables, seq_lens,
                    sm_scale=None):
    """Public entry: kernel on TPU / under the interpreter, masked-lane
    reference math elsewhere (the CPU test suite pins the reference
    bit-identical to dense attention on the valid region)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_ok():
        return _paged_attention_pallas(q, k_cache, v_cache, block_tables,
                                       seq_lens, sm_scale)
    return paged_attention_reference(q, k_cache, v_cache, block_tables,
                                     seq_lens, sm_scale)


# ---------------------------------------------------------------------------
# registered ops (the decode/prefill program building blocks)
# ---------------------------------------------------------------------------

@register_op("paged_attention", propagate_seqlen=False)
def _paged_attention_op(ctx, Q, K, V, KCache, VCache, BlockTables, SeqLens):
    """One decode step. Q/K/V: [slots, d_model] — this step's token per
    slot. Appends K/V at position seq_len-1 (in place: KCacheOut/VCacheOut
    alias the cache vars, so the executor donates the HBM buffers), then
    attends over [0, seq_len) through the block table. attrs: num_heads,
    sm_scale."""
    H = int(ctx.attr("num_heads", 1))
    S, D = Q.shape
    Dh = D // H
    sm_scale = float(ctx.attr("sm_scale", 1.0 / math.sqrt(Dh)))
    seq = SeqLens.astype(jnp.int32)
    bt = BlockTables.astype(jnp.int32)
    kc, vc = kv_cache_append(KCache, VCache, K.reshape(S, H, Dh),
                             V.reshape(S, H, Dh), bt, seq)
    out = paged_attention(Q.reshape(S, H, Dh), kc, vc, bt, seq, sm_scale)
    return {"Out": out.reshape(S, D), "KCacheOut": kc, "VCacheOut": vc}


@register_op("prefill_attention", propagate_seqlen=False)
def _prefill_attention_op(ctx, Q, K, V, KCache, VCache, BlockTables,
                          SeqLens):
    """Prompt phase. Q/K/V: [rows, T, d_model] at a bucket-ladder rung.
    Runs causal attention over the padded prompt (right-padding is
    invisible to valid positions under the causal mask) and scatters each
    row's K/V into its blocks in the same step. attrs: num_heads,
    sm_scale."""
    H = int(ctx.attr("num_heads", 1))
    B, T, D = Q.shape
    Dh = D // H
    sm_scale = float(ctx.attr("sm_scale", 1.0 / math.sqrt(Dh)))
    seq = SeqLens.astype(jnp.int32)
    bt = BlockTables.astype(jnp.int32)
    k4 = K.reshape(B, T, H, Dh)
    v4 = V.reshape(B, T, H, Dh)
    out = flash_attention(
        Q.reshape(B, T, H, Dh).transpose(0, 2, 1, 3),
        k4.transpose(0, 2, 1, 3), v4.transpose(0, 2, 1, 3),
        jnp.int32(0), True, sm_scale, 0.0)
    kc, vc = kv_cache_prefill_write(KCache, VCache, k4, v4, bt, seq)
    return {"Out": out.transpose(0, 2, 1, 3).reshape(B, T, D),
            "KCacheOut": kc, "VCacheOut": vc}


@register_op("gather_last_token", propagate_seqlen=False)
def _gather_last_token(ctx, X, SeqLens):
    """X: [rows, T, D] -> Out: [rows, D], each row's position
    seq_len - 1 (clamped into range; rows with seq_len 0 read position 0
    — callers never use their output)."""
    idx = jnp.clip(SeqLens.astype(jnp.int32) - 1, 0, X.shape[1] - 1)
    return {"Out": jnp.take_along_axis(
        X, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]}


# ---------------------------------------------------------------------------
# fluid-torrent: int8-quantized KV residency (per-BLOCK abs-max scale)
# ---------------------------------------------------------------------------
# The cache arrays become int8 [NB, BS, H, Dh] with one float32 scale
# per block ([NB], separate K and V scales): value = int8 * scale[block].
# Same symmetric +-127 bins as the wire codec (EQuARX idiom), but the
# quantization GROUP is the residency unit — a block — so a block's
# scale travels with it over the wire and a decode replica can admit a
# streamed block without requantizing.
#
# Invariants:
# - prefill OWNS its blocks: the write SETS each written block's scale
#   to its group abs-max/127 (a recycled block's stale scale is
#   overwritten, never consulted);
# - decode append GROWS a block: the first token written into a block
#   sets its scale fresh; a later token may RAISE it (never lower —
#   already-quantized neighbors would lose range), in which case the
#   block's resident int8 values are requantized by old/new and the
#   event is counted (RequantCountOut — the serve engine meters it as
#   serve_kv_requant_events_total; frequent requants mean the rounding
#   error budget is being spent, see docs/TORRENT.md);
# - attention DEQUANTIZES at the gather: Q and the in-flight K/V stay
#   float32 (prefill's own attention runs on the exact fp K/V — only
#   RESIDENCY is quantized), so the first generated token is exact and
#   quantization error enters through decode-step history reads only.

_Q8_BINS = 127.0


def _q8_append_one(cache, scale, new, block_tables, seq_lens):
    """Append one token's values per slot into an int8 cache.
    `new`: [S, H, Dh] float32. Returns (cache, scale, n_requant)."""
    bs = cache.shape[1]
    pos = jnp.maximum(seq_lens - 1, 0)
    blk = jnp.take_along_axis(block_tables, (pos // bs)[:, None],
                              axis=1)[:, 0]
    active = seq_lens > 0
    blk = jnp.where(active, blk, 0)
    off = jnp.where(active, pos % bs, 0)
    first = (pos % bs) == 0            # first token written into the block
    tok = new.astype(jnp.float32)
    needed = jnp.max(jnp.abs(tok), axis=(1, 2)) / _Q8_BINS        # [S]
    old = scale[blk]                                              # [S]
    base = jnp.where(first, jnp.float32(0.0), old)
    s_new = jnp.maximum(base, needed)
    requant = active & (~first) & (needed > old)
    # requantize the whole resident block where its scale grew; ratio 1
    # elsewhere makes the rewrite an exact identity (and the conflicting
    # inactive-slot writes all target trash block 0 with ratio 1)
    ratio = jnp.where(requant, old / jnp.maximum(s_new, 1e-30),
                      jnp.float32(1.0))
    adj = jnp.rint(cache[blk].astype(jnp.float32)
                   * ratio[:, None, None, None])
    cache = cache.at[blk].set(adj.astype(cache.dtype))
    safe = jnp.where(s_new > 0, s_new, jnp.float32(1.0))
    q = jnp.rint(jnp.clip(tok / safe[:, None, None], -_Q8_BINS, _Q8_BINS))
    cache = cache.at[blk, off].set(q.astype(cache.dtype))
    scale = scale.at[blk].set(jnp.where(active, s_new, old))
    return cache, scale, jnp.sum(requant.astype(jnp.int32))


def _q8_prefill_write_one(cache, scale, x, block_tables, seq_lens):
    """Scatter a padded prompt's values ([B, T, H, Dh]) into an int8
    cache, setting each written block's scale to its group abs-max."""
    bs = cache.shape[1]
    B, T = x.shape[0], x.shape[1]
    n_ord = -(-T // bs)
    t = jnp.arange(T)
    valid = t[None, :] < seq_lens[:, None]                        # [B, T]
    blk = jnp.take_along_axis(
        block_tables, jnp.broadcast_to((t // bs)[None, :], (B, T)), axis=1)
    blk = jnp.where(valid, blk, 0)
    off = jnp.broadcast_to((t % bs)[None, :], (B, T))
    xm = jnp.where(valid[:, :, None, None], x.astype(jnp.float32), 0.0)
    pad = n_ord * bs - T
    xp = jnp.pad(xm, ((0, 0), (0, pad), (0, 0), (0, 0))) if pad else xm
    grp = xp.reshape(B, n_ord, bs, x.shape[2], x.shape[3])
    needed = jnp.max(jnp.abs(grp), axis=(2, 3, 4)) / _Q8_BINS  # [B, n_ord]
    safe = jnp.where(needed > 0, needed, jnp.float32(1.0))
    per_pos = jnp.repeat(safe, bs, axis=1)[:, :T]                 # [B, T]
    q = jnp.rint(jnp.clip(xm / per_pos[:, :, None, None],
                          -_Q8_BINS, _Q8_BINS))
    cache = cache.at[blk.reshape(-1), off.reshape(-1)].set(
        q.reshape((B * T,) + x.shape[2:]).astype(cache.dtype))
    # overwrite the scale of every block that received a valid position
    # (prefill owns the block); rows/ordinals past seq_len redirect to
    # trash block 0 where they rewrite its existing scale
    has = (jnp.arange(n_ord)[None, :] * bs) < seq_lens[:, None]
    blk_sc = jnp.where(has, block_tables[:, :n_ord], 0)
    scale = scale.at[blk_sc.reshape(-1)].set(
        jnp.where(has, needed, scale[blk_sc]).reshape(-1))
    return cache, scale


def paged_attention_q8_reference(q, k_cache, v_cache, k_scale, v_scale,
                                 block_tables, seq_lens, sm_scale):
    """Reference math of the quantized decode read: gather int8 blocks
    through the table, dequantize by per-block scale, then the same
    masked softmax as paged_attention_reference."""
    S, H, Dh = q.shape
    nb, bs = k_cache.shape[0], k_cache.shape[1]
    T = block_tables.shape[1] * bs
    flat = (block_tables[:, :, None] * bs
            + jnp.arange(bs)[None, None, :]).reshape(S, T)
    ks = jnp.repeat(k_scale[block_tables], bs, axis=1)            # [S, T]
    vs = jnp.repeat(v_scale[block_tables], bs, axis=1)
    k = jnp.take(k_cache.reshape(nb * bs, H, Dh), flat,
                 axis=0).astype(jnp.float32) * ks[:, :, None, None]
    v = jnp.take(v_cache.reshape(nb * bs, H, Dh), flat,
                 axis=0).astype(jnp.float32) * vs[:, :, None, None]
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32), k) * sm_scale
    mask = jnp.arange(T)[None, :] < seq_lens[:, None]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("sht,sthd->shd", p, v) \
        / jnp.maximum(l, 1e-20)[..., 0][..., None]
    o = jnp.where((seq_lens > 0)[:, None, None], o, 0.0)
    return o.astype(q.dtype)


def _paged_decode_kernel_q8(seq_lens_ref, bt_ref, ks_ref, vs_ref, q_ref,
                            k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc, *,
                            sm_scale, block_size):
    """The _paged_decode_kernel with two more scalar-prefetch operands:
    the per-block K/V scales ride SMEM next to the block table, and the
    streamed int8 tile dequantizes in VMEM right after the load — the
    grid, index maps and online-softmax carry are unchanged."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    seq_len = seq_lens_ref[s]
    live = j * block_size < seq_len

    @pl.when(live)
    def _update():
        blk = bt_ref[s, j]
        q = q_ref[0]                                    # [H, Dh]
        k = k_ref[0].astype(jnp.float32) * ks_ref[blk]  # [BS, H, Dh]
        v = v_ref[0].astype(jnp.float32) * vs_ref[blk]
        scores = jnp.einsum(
            "hd,bhd->hb", q.astype(jnp.float32), k,
            preferred_element_type=jnp.float32) * sm_scale
        pos = j * block_size + lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(pos < seq_len, scores, NEG_INF)
        m = m_sc[...]
        m_new = jnp.maximum(m, jnp.max(scores, axis=1))
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_sc[...] = l_sc[...] * alpha + jnp.sum(p, axis=1)
        acc_sc[...] = acc_sc[...] * alpha[:, None] + jnp.einsum(
            "hb,bhd->hd", p, v, preferred_element_type=jnp.float32)
        m_sc[...] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.maximum(l_sc[...], 1e-20)
        o_ref[0] = (acc_sc[...] / l[:, None]).astype(o_ref.dtype)


def _paged_attention_q8_pallas(q, k_cache, v_cache, k_scale, v_scale,
                               block_tables, seq_lens, sm_scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, Dh = q.shape
    bs = k_cache.shape[1]
    max_b = block_tables.shape[1]
    kernel = functools.partial(_paged_decode_kernel_q8, sm_scale=sm_scale,
                               block_size=bs)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(S, max_b),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda s, j, sl, bt, ks, vs: (s, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh),
                         lambda s, j, sl, bt, ks, vs: (bt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, H, Dh),
                         lambda s, j, sl, bt, ks, vs: (bt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, Dh),
                               lambda s, j, sl, bt, ks, vs: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H,), jnp.float32),
            pltpu.VMEM((H,), jnp.float32),
            pltpu.VMEM((H, Dh), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, Dh), q.dtype),
        interpret=_interpret(),
    )(seq_lens.astype(jnp.int32), block_tables.astype(jnp.int32),
      k_scale.astype(jnp.float32), v_scale.astype(jnp.float32),
      q, k_cache, v_cache)


def paged_attention_q8(q, k_cache, v_cache, k_scale, v_scale, block_tables,
                       seq_lens, sm_scale=None):
    """Quantized-residency decode read: kernel on TPU / under the
    interpreter, dequantizing reference math elsewhere."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_ok():
        return _paged_attention_q8_pallas(q, k_cache, v_cache, k_scale,
                                          v_scale, block_tables, seq_lens,
                                          sm_scale)
    return paged_attention_q8_reference(q, k_cache, v_cache, k_scale,
                                        v_scale, block_tables, seq_lens,
                                        sm_scale)


@register_op("paged_attention_q8", propagate_seqlen=False)
def _paged_attention_q8_op(ctx, Q, K, V, KCache, VCache, KScale, VScale,
                           RequantCount, BlockTables, SeqLens):
    """One decode step over int8 caches. Same contract as
    paged_attention plus per-block scale vars ([num_blocks] f32, updated
    in place alongside their cache) and a [1] int32 requant-event
    counter the serve engine meters."""
    H = int(ctx.attr("num_heads", 1))
    S, D = Q.shape
    Dh = D // H
    sm_scale = float(ctx.attr("sm_scale", 1.0 / math.sqrt(Dh)))
    seq = SeqLens.astype(jnp.int32)
    bt = BlockTables.astype(jnp.int32)
    kc, ks, n_k = _q8_append_one(KCache, KScale, K.reshape(S, H, Dh),
                                 bt, seq)
    vc, vs, n_v = _q8_append_one(VCache, VScale, V.reshape(S, H, Dh),
                                 bt, seq)
    out = paged_attention_q8(Q.reshape(S, H, Dh), kc, vc, ks, vs, bt, seq,
                             sm_scale)
    return {"Out": out.reshape(S, D), "KCacheOut": kc, "VCacheOut": vc,
            "KScaleOut": ks, "VScaleOut": vs,
            "RequantCountOut": RequantCount + (n_k + n_v)}


@register_op("prefill_attention_q8", propagate_seqlen=False)
def _prefill_attention_q8_op(ctx, Q, K, V, KCache, VCache, KScale, VScale,
                             BlockTables, SeqLens):
    """Prompt phase over int8 caches: attention runs on the exact fp
    K/V in flight (prefill logits — and therefore the first token — are
    bit-identical to the fp cache), quantization happens only at the
    residency write. No requant counter: prefill always owns the blocks
    it writes."""
    H = int(ctx.attr("num_heads", 1))
    B, T, D = Q.shape
    Dh = D // H
    sm_scale = float(ctx.attr("sm_scale", 1.0 / math.sqrt(Dh)))
    seq = SeqLens.astype(jnp.int32)
    bt = BlockTables.astype(jnp.int32)
    k4 = K.reshape(B, T, H, Dh)
    v4 = V.reshape(B, T, H, Dh)
    out = flash_attention(
        Q.reshape(B, T, H, Dh).transpose(0, 2, 1, 3),
        k4.transpose(0, 2, 1, 3), v4.transpose(0, 2, 1, 3),
        jnp.int32(0), True, sm_scale, 0.0)
    kc, ks = _q8_prefill_write_one(KCache, KScale, k4, bt, seq)
    vc, vs = _q8_prefill_write_one(VCache, VScale, v4, bt, seq)
    return {"Out": out.transpose(0, 2, 1, 3).reshape(B, T, D),
            "KCacheOut": kc, "VCacheOut": vc,
            "KScaleOut": ks, "VScaleOut": vs}
