"""Pallas TPU dropout: in-kernel PRNG, mask regenerated in backward.

The reference dropout kernel (operators/dropout_op.cu) draws from a cuRAND
Philox stream and stores the mask tensor for the backward pass. On TPU the
expensive parts are (a) generating random bits through XLA's RNG (a long
integer-op chain on the VPU that cannot ride the MXU) and (b) a full
mask-tensor round trip through HBM. This kernel sidesteps both: each tile
seeds the hardware PRNG from (step_seed, tile_index) and draws its bits in
VMEM, and the backward kernel re-derives the identical mask from the same
seed instead of loading a stored one — dropout becomes a pure
read-x/write-y elementwise pass at HBM speed.

Same tile-hash re-seeding scheme as ops/pallas_attention.py so masks are
independent of grid iteration order.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_attention import _HASH_A, _HASH_B

_LANES = 128
# target elements per grid step (~512 KB bf16 blocks)
_BLOCK_ELEMS = 2048 * 128


def _mask_for_tile(seed_ref, tile_idx, shape, rate):
    from jax.experimental.pallas import tpu as pltpu

    s = seed_ref[0, 0] * _HASH_A + tile_idx * _HASH_B
    pltpu.prng_seed(s * _HASH_A)
    bits = pltpu.prng_random_bits(shape)
    thresh = int(min(max(-2 ** 31 + rate * 2 ** 32, -2 ** 31), 2 ** 31 - 1))
    return bits >= jnp.int32(thresh)


def _dropout_kernel(seed_ref, x_ref, o_ref, *, rate):
    from jax.experimental import pallas as pl

    keep = _mask_for_tile(seed_ref, pl.program_id(0), x_ref.shape, rate)
    inv = 1.0 / (1.0 - rate)
    x = x_ref[...]
    o_ref[...] = jnp.where(keep, x * jnp.asarray(inv, x.dtype),
                           jnp.zeros_like(x))


def _run(x2d, seed, rate, interpret):
    from jax.experimental import pallas as pl

    rows, cols = x2d.shape
    # keep the tensor's own minor dim as the lane dim — reshaping to a
    # different minor dim would be a physical relayout (a full HBM copy,
    # which is exactly what this kernel exists to avoid)
    block_rows = max(1, min(rows, _BLOCK_ELEMS // cols))
    grid = (rows + block_rows - 1) // block_rows
    kern = functools.partial(_dropout_kernel, rate=rate)
    return pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=interpret,
    )(seed, x2d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def dropout_tpu(x, seed, rate, interpret=False):
    """Upscale-in-train dropout via the Pallas kernel.

    x: any shape with total size divisible by 128. seed: int32 array shaped
    (1, 1) (scalar-prefetch style, like the flash kernels).
    """
    return _fwd(x, seed, rate, interpret)[0]


def _fwd(x, seed, rate, interpret):
    x2d = x.reshape(-1, x.shape[-1])     # free: minor dim unchanged
    out = _run(x2d, seed, rate, interpret).reshape(x.shape)
    return out, (seed,)


def _bwd(rate, interpret, res, dy):
    (seed,) = res
    dy2d = dy.reshape(-1, dy.shape[-1])
    dx = _run(dy2d, seed, rate, interpret).reshape(dy.shape)
    return dx, None


dropout_tpu.defvjp(_fwd, _bwd)


def supports(x, rate) -> bool:
    """Kernel applicability: a lane-aligned minor dim (so the 2D view is
    layout-free) and a nontrivial rate."""
    if not (0.0 < rate < 1.0) or not x.shape:
        return False
    return x.shape[-1] % _LANES == 0 and int(np.prod(x.shape)) > 0
