"""Math / elementwise / activation / reduction op lowerings.

Capability parity with the reference op families (reference:
paddle/fluid/operators/elementwise_*.cc, mul_op.cc, matmul_op.cc, scale_op.cc,
sum_op.cc, activation_op.cc, reduce_op.cc, softmax_op.cc, top_k_op.cc, ...).
Each op here is a pure JAX lowering rule; XLA fuses them into surrounding
computations (the reference needed per-op CUDA kernels + manual fusion).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core import types


def _align_y(X, Y, axis):
    """Reference elementwise broadcast semantics (elementwise_op_function.h):
    Y's dims match a contiguous run of X's dims starting at `axis`."""
    if Y.ndim == 0 or X.shape == Y.shape or Y.ndim == X.ndim:
        return Y
    axis = int(axis)
    if axis < 0:
        axis = X.ndim - Y.ndim
    shape = [1] * axis + list(Y.shape) + [1] * (X.ndim - axis - Y.ndim)
    return Y.reshape(shape)


def _register_elementwise(name, fn):
    @register_op(name)
    def _rule(ctx, X, Y, _fn=fn):
        return {"Out": _fn(X, _align_y(X, Y, ctx.attr("axis", -1)))}
    _rule.__name__ = name
    return _rule


_register_elementwise("elementwise_add", jnp.add)
_register_elementwise("elementwise_sub", jnp.subtract)
_register_elementwise("elementwise_mul", jnp.multiply)
_register_elementwise("elementwise_div", jnp.divide)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_pow", jnp.power)
_register_elementwise("elementwise_mod", jnp.mod)
_register_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("mul")
def _mul(ctx, X, Y):
    """Flattening matmul (reference mul_op.cc): X flattened at
    x_num_col_dims, Y at y_num_col_dims.

    Lowered as ONE dot_general with multi-dim contraction instead of
    reshape->2D-GEMM->reshape: the 2-D round trip is a cuBLAS-ism, and on
    TPU the flattened result's tiled layout forced a physical copy on
    every downstream reshape+transpose (attention head splits were ~5 ms
    of `copy` ops per transformer-base step; an interleaved A/B measured
    the contraction form faster and far steadier)."""
    xd = ctx.attr("x_num_col_dims", 1)
    yd = ctx.attr("y_num_col_dims", 1)
    if X.dtype != Y.dtype:
        dt = jnp.result_type(X.dtype, Y.dtype)
        X, Y = X.astype(dt), Y.astype(dt)
    if X.shape[xd:] == Y.shape[:yd]:
        out = lax.dot_general(
            X, Y,
            dimension_numbers=((tuple(range(xd, X.ndim)), tuple(range(yd))),
                               ((), ())))
        return {"Out": out}
    # contraction only matches after flattening (e.g. conv features [C,H,W]
    # against a pre-flattened [C*H*W, M] weight): reshape-GEMM-reshape
    import math as _m
    xs, ys = X.shape, Y.shape
    x2 = X.reshape((_m.prod(xs[:xd]), _m.prod(xs[xd:])))
    y2 = Y.reshape((_m.prod(ys[:yd]), _m.prod(ys[yd:])))
    return {"Out": (x2 @ y2).reshape(xs[:xd] + ys[yd:])}


@register_op("matmul")
def _matmul(ctx, X, Y):
    tx, ty = ctx.attr("transpose_X", False), ctx.attr("transpose_Y", False)
    alpha = ctx.attr("alpha", 1.0)
    a = jnp.swapaxes(X, -1, -2) if tx else X
    b = jnp.swapaxes(Y, -1, -2) if ty else Y
    out = jnp.matmul(a, b)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("scale")
def _scale(ctx, X):
    s, b = ctx.attr("scale", 1.0), ctx.attr("bias", 0.0)
    if ctx.attr("bias_after_scale", True):
        return {"Out": X * s + b}
    return {"Out": (X + b) * s}


@register_op("sum")
def _sum(ctx, X):
    xs = X if isinstance(X, list) else [X]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean")
def _mean(ctx, X):
    return {"Out": jnp.mean(X).reshape((1,))}


@register_op("cast")
def _cast(ctx, X):
    return {"Out": X.astype(types.np_dtype(ctx.attr("out_dtype", "float32")))}


@register_op("clip")
def _clip(ctx, X):
    return {"Out": jnp.clip(X, ctx.attr("min"), ctx.attr("max"))}


@register_op("clip_by_norm")
def _clip_by_norm(ctx, X):
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(X * X))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": X * scale}


def _reduce(ctx, X, fn):
    dims = ctx.attr("dim", [0])
    keep = ctx.attr("keep_dim", False)
    if ctx.attr("reduce_all", False):
        out = fn(X)
        return out.reshape((1,)) if not keep else out.reshape((1,) * X.ndim)
    dims = tuple(dims) if isinstance(dims, (list, tuple)) else (dims,)
    return fn(X, axis=dims, keepdims=keep)


for _name, _fn in [("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min),
                   ("reduce_prod", jnp.prod)]:
    def _make(fn):
        def rule(ctx, X):
            return {"Out": _reduce(ctx, X, fn)}
        return rule
    register_op(_name)(_make(_fn))


# -- activations (reference activation_op.cc) -------------------------------

def _register_act(name, fn):
    @register_op(name)
    def _rule(ctx, X, _fn=fn):
        return {"Out": _fn(ctx, X)}
    return _rule


_register_act("relu", lambda ctx, x: jax.nn.relu(x))
_register_act("relu6", lambda ctx, x: jnp.clip(x, 0.0, ctx.attr("threshold", 6.0)))
_register_act("sigmoid", lambda ctx, x: jax.nn.sigmoid(x))
_register_act("logsigmoid", lambda ctx, x: jax.nn.log_sigmoid(x))
_register_act("tanh", lambda ctx, x: jnp.tanh(x))
_register_act("tanh_shrink", lambda ctx, x: x - jnp.tanh(x))
_register_act("exp", lambda ctx, x: jnp.exp(x))
_register_act("log", lambda ctx, x: jnp.log(x))
_register_act("sqrt", lambda ctx, x: jnp.sqrt(x))
_register_act("rsqrt", lambda ctx, x: lax.rsqrt(x))
_register_act("abs", lambda ctx, x: jnp.abs(x))
_register_act("square", lambda ctx, x: jnp.square(x))
_register_act("reciprocal", lambda ctx, x: 1.0 / x)
_register_act("sign", lambda ctx, x: jnp.sign(x))
_register_act("floor", lambda ctx, x: jnp.floor(x))
_register_act("ceil", lambda ctx, x: jnp.ceil(x))
_register_act("round", lambda ctx, x: jnp.round(x))
_register_act("cos", lambda ctx, x: jnp.cos(x))
_register_act("sin", lambda ctx, x: jnp.sin(x))
_register_act("softplus", lambda ctx, x: jax.nn.softplus(x))
_register_act("softsign", lambda ctx, x: x / (1.0 + jnp.abs(x)))
_register_act("gelu", lambda ctx, x: jax.nn.gelu(x, approximate=False))
_register_act("leaky_relu", lambda ctx, x: jnp.where(x >= 0, x, x * ctx.attr("alpha", 0.02)))
_register_act("elu", lambda ctx, x: jax.nn.elu(x, alpha=ctx.attr("alpha", 1.0)))
_register_act("swish", lambda ctx, x: x * jax.nn.sigmoid(ctx.attr("beta", 1.0) * x))
_register_act("hard_sigmoid",
              lambda ctx, x: jnp.clip(ctx.attr("slope", 0.2) * x + ctx.attr("offset", 0.5),
                                      0.0, 1.0))
_register_act("brelu", lambda ctx, x: jnp.clip(x, ctx.attr("t_min", 0.0),
                                               ctx.attr("t_max", 24.0)))
_register_act("soft_relu",
              lambda ctx, x: jnp.log(1 + jnp.exp(jnp.clip(x, -ctx.attr("threshold", 40.0),
                                                          ctx.attr("threshold", 40.0)))))
_register_act("pow", lambda ctx, x: jnp.power(x, ctx.attr("factor", 1.0)))
_register_act("hard_shrink",
              lambda ctx, x: jnp.where(jnp.abs(x) > ctx.attr("threshold", 0.5), x, 0.0))
_register_act("softshrink",
              lambda ctx, x: jnp.where(x > ctx.attr("lambda", 0.5), x - ctx.attr("lambda", 0.5),
                                       jnp.where(x < -ctx.attr("lambda", 0.5),
                                                 x + ctx.attr("lambda", 0.5), 0.0)))
_register_act("thresholded_relu",
              lambda ctx, x: jnp.where(x > ctx.attr("threshold", 1.0), x, 0.0))


@register_op("prelu")
def _prelu(ctx, X, Alpha):
    mode = ctx.attr("mode", "all")
    if mode == "channel" and Alpha.ndim == 1 and X.ndim == 4:
        alpha = Alpha.reshape((1, -1, 1, 1))
    else:
        alpha = Alpha
    return {"Out": jnp.where(X >= 0, X, X * alpha)}


@register_op("softmax")
def _softmax(ctx, X):
    return {"Out": jax.nn.softmax(X, axis=ctx.attr("axis", -1))}


@register_op("log_softmax")
def _log_softmax(ctx, X):
    return {"Out": jax.nn.log_softmax(X, axis=ctx.attr("axis", -1))}


@register_op("cumsum")
def _cumsum(ctx, X):
    axis = ctx.attr("axis", -1)
    out = jnp.cumsum(X, axis=axis)
    if ctx.attr("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(X, axis), axis=axis), axis)
    if ctx.attr("exclusive", False):
        out = out - X
    return {"Out": out}


@register_op("top_k", propagate_seqlen=False)
def _top_k(ctx, X):
    vals, idx = lax.top_k(X, ctx.attr("k", 1))
    return {"Out": vals, "Indices": idx.astype(types.index_dtype())}


@register_op("arg_max", propagate_seqlen=False)
def _arg_max(ctx, X):
    return {"Out": jnp.argmax(X, axis=ctx.attr("axis", -1)).astype(types.index_dtype())}


@register_op("arg_min", propagate_seqlen=False)
def _arg_min(ctx, X):
    return {"Out": jnp.argmin(X, axis=ctx.attr("axis", -1)).astype(types.index_dtype())}


# -- comparisons / logicals (reference compare_op.cc, logical_op.cc) --------

def _register_cmp(name, fn):
    @register_op(name)
    def _rule(ctx, X, Y, _fn=fn):
        return {"Out": _fn(X, _align_y(X, Y, ctx.attr("axis", -1)))}
    return _rule


_register_cmp("equal", jnp.equal)
_register_cmp("not_equal", jnp.not_equal)
_register_cmp("less_than", jnp.less)
_register_cmp("less_equal", jnp.less_equal)
_register_cmp("greater_than", jnp.greater)
_register_cmp("greater_equal", jnp.greater_equal)
_register_cmp("logical_and", jnp.logical_and)
_register_cmp("logical_or", jnp.logical_or)
_register_cmp("logical_xor", jnp.logical_xor)


@register_op("logical_not")
def _logical_not(ctx, X):
    return {"Out": jnp.logical_not(X)}


@register_op("isfinite")
def _isfinite(ctx, X):
    xs = X if isinstance(X, list) else [X]
    ok = jnp.array(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {"Out": ok.reshape((1,))}


@register_op("maximum")
def _maximum(ctx, X, Y):
    return {"Out": jnp.maximum(X, Y)}


@register_op("l2_normalize")
def _l2_normalize(ctx, X):
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(X * X, axis=axis, keepdims=True))
    return {"Out": X / jnp.maximum(norm, eps), "Norm": norm}


@register_op("cos_sim")
def _cos_sim(ctx, X, Y):
    xn = jnp.sqrt(jnp.sum(X * X, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(Y * Y, axis=-1, keepdims=True))
    out = jnp.sum(X * Y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}
