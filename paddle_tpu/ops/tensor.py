"""Tensor creation / manipulation op lowerings.

Capability parity with the reference's fill/reshape/concat/... op family
(reference: paddle/fluid/operators/{fill_constant,uniform_random,
gaussian_random,reshape,transpose,concat,split,slice,gather,expand,one_hot,
lookup_table,...}_op.cc).

Random ops consume the functional PRNG key threaded by the executor
(replacing the reference's per-device cuRAND generators / `random_seed`).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op, register_grad
from ..core import types


@register_op("fill_constant")
def _fill_constant(ctx, X=None):
    shape = [int(s) for s in ctx.attr("shape", [1])]
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jnp.full(shape, ctx.attr("value", 0.0), dtype)}


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx, Input):
    shape = [int(s) for s in ctx.attr("shape")]
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = Input.shape[in_idx]
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jnp.full(shape, ctx.attr("value", 0.0), dtype)}


@register_op("uniform_random", needs_rng=True)
def _uniform_random(ctx, X=None):
    shape = tuple(int(s) for s in ctx.attr("shape"))
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    return {"Out": jax.random.uniform(ctx.key, shape, dtype, lo, hi)}


@register_op("gaussian_random", needs_rng=True)
def _gaussian_random(ctx, X=None):
    shape = tuple(int(s) for s in ctx.attr("shape"))
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    return {"Out": mean + std * jax.random.normal(ctx.key, shape, dtype)}


@register_op("truncated_gaussian_random", needs_rng=True)
def _truncated_gaussian_random(ctx, X=None):
    shape = tuple(int(s) for s in ctx.attr("shape"))
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    z = jax.random.truncated_normal(ctx.key, -2.0, 2.0, shape, dtype)
    return {"Out": mean + std * z}


@register_op("assign")
def _assign(ctx, X):
    return {"Out": X}


@register_op("assign_value")
def _assign_value(ctx):
    import numpy as np
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    shape = ctx.attr("shape")
    vals = ctx.attr("values")
    return {"Out": jnp.asarray(np.array(vals, dtype).reshape(shape))}


@register_op("shape", propagate_seqlen=False)
def _shape(ctx, Input):
    return {"Out": jnp.array(Input.shape, types.index_dtype())}


def _reshape_infer(ctx, structs):
    """Exact static-shape rule. eval_shape can't be used here: the dynamic
    batch dim is substituted with a prime sentinel, and a target like
    [-1, K] would need SENTINEL % K == 0. With a dynamic input dim, the -1
    output dim is simply dynamic — runtime shapes are authoritative.
    `ctx.dim_sentinel` is whichever sentinel THIS trace substituted
    (infer_op_shapes runs two traces to classify dynamic dims)."""
    import math as _m

    sentinel = ctx.dim_sentinel
    X = structs["X"][0]
    target = [int(s) for s in ctx.attr("shape")]
    target = [int(X.shape[i]) if s == 0 else s
              for i, s in enumerate(target)]
    dynamic_in = any(d >= sentinel and d % sentinel == 0
                     for d in X.shape)
    if -1 in target:
        known = _m.prod(d for d in target if d != -1)
        neg = target.index(-1)
        total = _m.prod(int(d) for d in X.shape)
        if known and total % known == 0:
            # exact: stays a sentinel multiple when the -1 absorbs the
            # dynamic batch, yields the true static dim when it doesn't
            # (e.g. reshape([0, -1]) of a [-1, 4, 8] input -> (-1, 32))
            target[neg] = total // known
        elif dynamic_in:
            target[neg] = sentinel
        else:
            raise ValueError(
                f"reshape: cannot infer -1 dim reshaping {tuple(X.shape)} "
                f"to {ctx.attr('shape')}")
    elif dynamic_in:
        # all-target-dims-concrete reshape of a dynamic tensor: the dim
        # that absorbs the batch is unknowable statically; leave the
        # declared target (runtime authoritative)
        pass
    return {"Out": jax.ShapeDtypeStruct(tuple(target), X.dtype)}


@register_op("reshape", infer=_reshape_infer)
def _reshape(ctx, X, Shape=None):
    shape = [int(s) for s in ctx.attr("shape")]
    # reference reshape_op.cc: 0 means "copy this dim from input".
    shape = [X.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": X.reshape(shape)}


@register_op("squeeze")
def _squeeze(ctx, X):
    axes = ctx.attr("axes", [])
    if axes:
        return {"Out": jnp.squeeze(X, axis=tuple(axes))}
    return {"Out": jnp.squeeze(X)}


@register_op("unsqueeze")
def _unsqueeze(ctx, X):
    out = X
    for a in sorted(ctx.attr("axes")):
        out = jnp.expand_dims(out, a)
    return {"Out": out}


@register_op("flatten")
def _flatten(ctx, X):
    axis = ctx.attr("axis", 1)
    lead = math.prod(X.shape[:axis]) if axis > 0 else 1
    return {"Out": X.reshape((lead, -1))}


@register_op("transpose", propagate_seqlen=False)
def _transpose(ctx, X):
    return {"Out": jnp.transpose(X, ctx.attr("axis"))}


@register_op("concat")
def _concat(ctx, X):
    xs = X if isinstance(X, list) else [X]
    return {"Out": jnp.concatenate(xs, axis=ctx.attr("axis", 0))}


@register_op("split")
def _split(ctx, X):
    axis = ctx.attr("axis", 0)
    sections = ctx.attr("sections", [])
    num = ctx.attr("num", 0)
    if sections:
        idx = list(jnp.cumsum(jnp.array(sections))[:-1])
        outs = jnp.split(X, [int(i) for i in idx], axis=axis)
    else:
        outs = jnp.split(X, num, axis=axis)
    return {"Out": outs}


@register_op("stack")
def _stack(ctx, X):
    xs = X if isinstance(X, list) else [X]
    return {"Y": jnp.stack(xs, axis=ctx.attr("axis", 0))}


@register_op("unstack")
def _unstack(ctx, X):
    axis = ctx.attr("axis", 0)
    n = X.shape[axis]
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(X, n, axis=axis)]}


@register_op("slice", propagate_seqlen=False)
def _slice(ctx, Input):
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * Input.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = Input.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": Input[tuple(idx)]}


@register_op("gather", propagate_seqlen=False)
def _gather(ctx, X, Index):
    return {"Out": jnp.take(X, Index.reshape(-1).astype(jnp.int32), axis=0)}


@register_op("gather_nd", propagate_seqlen=False)
def _gather_nd(ctx, X, Index):
    idx = tuple(jnp.moveaxis(Index, -1, 0))
    return {"Out": X[idx]}


@register_op("scatter", propagate_seqlen=False)
def _scatter(ctx, X, Ids, Updates):
    ids = Ids.reshape(-1).astype(jnp.int32)
    if ctx.attr("overwrite", True):
        return {"Out": X.at[ids].set(Updates)}
    return {"Out": X.at[ids].add(Updates)}


@register_op("expand")
def _expand(ctx, X):
    times = ctx.attr("expand_times")
    return {"Out": jnp.tile(X, tuple(times))}


@register_op("expand_dims_tile")
def _expand_dims_tile(ctx, X):
    return {"Out": jnp.tile(X, tuple(ctx.attr("times")))}


@register_op("pad")
def _pad(ctx, X):
    paddings = ctx.attr("paddings")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(X.ndim)]
    return {"Out": jnp.pad(X, cfg, constant_values=val)}


@register_op("pad2d")
def _pad2d(ctx, X):
    p = ctx.attr("paddings", [0, 0, 0, 0])  # t, b, l, r (NCHW)
    mode = ctx.attr("mode", "constant")
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(X, cfg, constant_values=ctx.attr("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(X, cfg, mode=jmode)}


@register_op("one_hot", propagate_seqlen=False)
def _one_hot(ctx, X):
    depth = ctx.attr("depth")
    ids = X.reshape(X.shape[:-1]) if X.shape and X.shape[-1] == 1 else X
    return {"Out": jax.nn.one_hot(ids.astype(jnp.int32), depth, dtype=jnp.float32)}


@register_op("lookup_table", propagate_seqlen=True)
def _lookup_table(ctx, W, Ids):
    """Embedding lookup (reference lookup_table_op.cc). Ids has a trailing
    size-1 dim in the reference convention."""
    ids = Ids
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    ids = ids.astype(jnp.int32)
    out = jnp.take(W, ids, axis=0)
    pad = ctx.attr("padding_idx", -1)
    if pad is not None and pad >= 0:
        mask = (ids != pad)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": out}


@register_op("range")
def _range(ctx):
    return {"Out": jnp.arange(ctx.attr("start", 0), ctx.attr("end"),
                              ctx.attr("step", 1),
                              dtype=types.np_dtype(ctx.attr("dtype", "int64")))}


@register_op("increment")
def _increment(ctx, X):
    # keep X's dtype (int counters must stay int inside loop carries)
    return {"Out": X + jnp.asarray(ctx.attr("step", 1.0)).astype(X.dtype)}


@register_op("reverse")
def _reverse(ctx, X):
    return {"Out": jnp.flip(X, axis=tuple(ctx.attr("axis")))}


@register_op("sequence_mask", propagate_seqlen=False)
def _sequence_mask(ctx, X):
    maxlen = ctx.attr("maxlen", -1)
    if maxlen < 0:
        raise ValueError("sequence_mask requires a static maxlen on TPU")
    dtype = types.np_dtype(ctx.attr("out_dtype", "int64"))
    rng = jnp.arange(maxlen)
    return {"Y": (rng[None, :] < X.reshape(-1, 1)).astype(dtype)}


@register_op("batch_gather", propagate_seqlen=False)
def _batch_gather(ctx, X, Index):
    """Per-row gather along axis 1: X [B, K, ...], Index [B, K'] ->
    [B, K', ...] (beam-search parent reordering)."""
    idx = Index.astype(jnp.int32)
    while idx.ndim < X.ndim:
        idx = idx[..., None]
    return {"Out": jnp.take_along_axis(X, idx, axis=1)}


@register_op("causal_mask", propagate_seqlen=False)
def _causal_mask(ctx):
    """Additive upper-triangular attention mask, computed in-graph (constant-
    folded by XLA) instead of shipping a T*T blob through the IR."""
    t = int(ctx.attr("size"))
    neg = ctx.attr("neg", -1e9)
    row = jnp.arange(t)[:, None]
    col = jnp.arange(t)[None, :]
    mask = jnp.where(col > row, jnp.float32(neg), jnp.float32(0.0))
    return {"Out": mask.reshape(1, 1, t, t)}


@register_op("sinusoid_pos_encoding", propagate_seqlen=False)
def _sinusoid_pos_encoding(ctx):
    """Transformer sinusoidal position table [T, D], computed in-graph."""
    t = int(ctx.attr("size"))
    d = int(ctx.attr("d_model"))
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    i = jnp.arange(d, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, (2.0 * jnp.floor(i / 2.0)) / d)
    even = jnp.sin(angle)
    odd = jnp.cos(angle)
    enc = jnp.where(jnp.arange(d)[None, :] % 2 == 0, even, odd)
    return {"Out": enc}


@register_op("uniform_random_batch_size_like", needs_rng=True)
def _uniform_random_bsl(ctx, Input):
    shape = [int(s) for s in ctx.attr("shape")]
    shape[ctx.attr("output_dim_idx", 0)] = Input.shape[ctx.attr("input_dim_idx", 0)]
    dtype = types.np_dtype(ctx.attr("dtype", "float32"))
    return {"Out": jax.random.uniform(ctx.key, tuple(shape), dtype,
                                      ctx.attr("min", -1.0), ctx.attr("max", 1.0))}


@register_op("argsort")
def _argsort(ctx, X):
    """Sorted values + indices (reference argsort_op.cc). XLA lowers sort
    to an efficient TPU sorting network; the old "use top_k" guidance
    predated that and is retired."""
    axis = ctx.attr("axis", -1)
    idx = jnp.argsort(X, axis=axis)
    out = jnp.take_along_axis(X, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(types.index_dtype())}


@register_op("is_empty")
def _is_empty(ctx, X):
    """True iff the tensor holds zero elements (reference is_empty_op.cc).
    Shapes are static under XLA, so this folds to a constant."""
    import numpy as _np
    return {"Out": jnp.asarray(int(_np.prod(X.shape)) == 0).reshape((1,))}


@register_op("print")
def _print(ctx, X):
    """Runtime tensor printing (reference print_op.cc) via jax.debug.print:
    the callback fires from the compiled program on the host, so it works
    inside the single-XLA-step executor. Out aliases the input so the op
    can be inserted mid-graph without changing the math."""
    message = ctx.attr("message", "") or ""
    summarize = int(ctx.attr("summarize", -1))
    flat = X.reshape(-1)
    shown = flat[:summarize] if summarize > 0 else flat
    # user text goes through brace-escaping: it must never be interpreted
    # as format placeholders by jax.debug.print
    prefix = (message + "shape=" + str(tuple(X.shape))) \
        .replace("{", "{{").replace("}", "}}")
    if _runtime_print_supported():
        jax.debug.print(prefix + " {x}", x=shown)
    else:
        # e.g. the axon PJRT tunnel: no host send/recv callbacks — a
        # debug.print in the program would abort the whole step at run
        # time. Degrade to a trace-time banner (fires once per compile;
        # un-escaped text, this is a plain host print).
        print(f"[print op: {message}shape={tuple(X.shape)} — runtime value "
              f"printing unavailable on this backend]")
    return {"Out": X}


_PRINT_PROBE = None


def _runtime_print_supported() -> bool:
    """Whether the backend executes host callbacks (jax.debug.print).
    Probed once with a throwaway jit — backends that lack send/recv
    (the axon dev tunnel) raise UNIMPLEMENTED only at execution time and
    report their platform as plain 'tpu', so a name check cannot work."""
    global _PRINT_PROBE
    if _PRINT_PROBE is None:
        import numpy as _np

        def _f(x):
            jax.debug.print("{x}", x=x)
            return x + 1
        try:
            _np.asarray(jax.jit(_f)(jnp.zeros((1,), jnp.float32)))
            jax.effects_barrier()
            _PRINT_PROBE = True
        except Exception:
            _PRINT_PROBE = False
    return _PRINT_PROBE


@register_op("load")
def _load(ctx):
    """Load one np.save'd array (reference load_op.cc). The file is read at
    trace time and baked into the compiled step as a constant — re-run the
    startup/load program to pick up a changed file (same contract as the
    reference: load runs when its program runs)."""
    import numpy as _np
    path = ctx.attr("file_path")
    if not path.endswith(".npy"):
        try:
            arr = _np.load(path)
        except FileNotFoundError:
            arr = _np.load(path + ".npy")
    else:
        arr = _np.load(path)
    if ctx.attr("load_as_fp16"):
        arr = arr.astype(_np.float16)
    return {"Out": jnp.asarray(arr)}
