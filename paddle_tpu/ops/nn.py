"""NN compute op lowerings: conv / pool / norm / dropout.

Capability parity with the reference's cuDNN-backed kernels (reference:
paddle/fluid/operators/{conv_op.cc,conv_cudnn_op.cu.cc,pool_op.cc,
batch_norm_op.cc,layer_norm_op.cc,dropout_op.cc,lrn_op.cc}).

TPU-native redesign: convolutions map to `lax.conv_general_dilated`, which XLA
tiles onto the MXU directly (no cuDNN algorithm search, no workspace attr);
batch/layer norm are expressed in plain jnp so XLA fuses them into adjacent
convs; dropout uses the executor's functional PRNG keys.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op
from ..core import types


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


@register_op("conv2d", propagate_seqlen=False)
def _conv2d(ctx, Input, Filter, Bias=None):
    """Conv in NCHW or NHWC (reference conv_op.cc `data_format`). Filter is
    always stored OIHW so parameters are layout-independent; lax accepts the
    mixed dimension_numbers and XLA picks physical layouts for the MXU."""
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dils = _pair(ctx.attr("dilations", [1, 1]))
    groups = ctx.attr("groups", 1)
    fmt = ctx.attr("data_format", "NCHW")
    out = lax.conv_general_dilated(
        Input, Filter,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dils,
        dimension_numbers=(fmt, "OIHW", fmt),
        feature_group_count=groups,
    )
    if Bias is not None:
        bshape = (1, -1, 1, 1) if fmt == "NCHW" else (1, 1, 1, -1)
        out = out + Bias.reshape(bshape)
    return {"Output": out}


@register_op("depthwise_conv2d", propagate_seqlen=False)
def _depthwise_conv2d(ctx, Input, Filter, Bias=None):
    ctx.attrs = dict(ctx.attrs)
    c_axis = 1 if ctx.attr("data_format", "NCHW") == "NCHW" else 3
    ctx.attrs["groups"] = Input.shape[c_axis]
    return _conv2d(ctx, Input, Filter, Bias)


@register_op("conv2d_transpose", propagate_seqlen=False)
def _conv2d_transpose(ctx, Input, Filter, Bias=None):
    """Gradient-of-conv as a forward op (reference conv_transpose_op.cc).
    Filter layout follows the reference: [in_c, out_c, H, W]."""
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    dils = _pair(ctx.attr("dilations", [1, 1]))
    # Gradient-of-conv expressed directly: stride becomes lhs (input)
    # dilation, padding p becomes (k_eff-1-p) of the spatially-flipped
    # kernel, giving out = (in-1)*s + k_eff - 2p — the reference formula
    # (conv_transpose_op.cc). Filter stays in the reference [in_c, out_c,
    # H, W] layout ("IOHW"). Validated bit-exact (f64) against torch
    # conv_transpose2d over k/p/s/dilation combinations; lax.conv_transpose
    # was NOT used because its padding semantics diverge for k-1 != 2p.
    k_eff = [dils[i] * (Filter.shape[2 + i] - 1) + 1 for i in (0, 1)]
    out = lax.conv_general_dilated(
        Input, jnp.flip(Filter, axis=(2, 3)),
        window_strides=(1, 1),
        padding=[(k_eff[0] - 1 - pads[0], k_eff[0] - 1 - pads[0]),
                 (k_eff[1] - 1 - pads[1], k_eff[1] - 1 - pads[1])],
        lhs_dilation=strides,
        rhs_dilation=dils,
        dimension_numbers=("NCHW", "IOHW", "NCHW"),
    )
    if Bias is not None:
        out = out + Bias.reshape((1, -1, 1, 1))
    return {"Output": out}


@register_op("pool2d", propagate_seqlen=False)
def _pool2d(ctx, X):
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", [2, 2]))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = _pair(ctx.attr("paddings", [0, 0]))
    fmt = ctx.attr("data_format", "NCHW")
    spatial = (2, 3) if fmt == "NCHW" else (1, 2)
    if ctx.attr("global_pooling", False) or ctx.attr("adaptive", False):
        oh, ow = ksize if ctx.attr("adaptive", False) else (1, 1)
        h, w = X.shape[spatial[0]], X.shape[spatial[1]]
        if ctx.attr("adaptive", False) and (oh < 1 or ow < 1):
            raise ValueError(
                "adaptive pool2d needs an explicit positive pool_size "
                f"(the output grid); got {(oh, ow)}")
        if (oh, ow) == (1, 1):
            if ptype == "max":
                return {"Out": jnp.max(X, axis=spatial, keepdims=True)}
            return {"Out": jnp.mean(X, axis=spatial, keepdims=True)}
        # adaptive to (oh, ow): exact when the output divides the input —
        # each output cell reduces an equal (h/oh, w/ow) tile (the
        # reference's bin boundaries coincide in that case)
        if h % oh or w % ow:
            raise NotImplementedError(
                f"adaptive pool2d: output {(oh, ow)} must divide input "
                f"{(h, w)} on TPU (unequal bins need ragged windows)")
        if fmt == "NCHW":
            n, c = X.shape[0], X.shape[1]
            tiles = X.reshape(n, c, oh, h // oh, ow, w // ow)
            red_axes = (3, 5)
        else:
            n, c = X.shape[0], X.shape[3]
            tiles = X.reshape(n, oh, h // oh, ow, w // ow, c)
            red_axes = (2, 4)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(tiles, axis=red_axes)}
    if fmt == "NCHW":
        window = (1, 1) + ksize
        strides4 = (1, 1) + strides
        padcfg = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    else:
        window = (1,) + ksize + (1,)
        strides4 = (1,) + strides + (1,)
        padcfg = ((0, 0), (pads[0], pads[0]), (pads[1], pads[1]), (0, 0))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(X.dtype, jnp.floating) else jnp.iinfo(X.dtype).min
        out = lax.reduce_window(X, init, lax.max, window, strides4, padcfg)
        return {"Out": out}
    # avg pool
    ones = jnp.ones_like(X)
    ssum = lax.reduce_window(X, 0.0, lax.add, window, strides4, padcfg)
    if ctx.attr("exclusive", True):
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides4, padcfg)
    else:
        cnt = float(ksize[0] * ksize[1])
    return {"Out": ssum / cnt}


@register_op("batch_norm", propagate_seqlen=False)
def _batch_norm(ctx, X, Scale, Bias, Mean, Variance):
    """Reference batch_norm_op.cc. Outputs Y plus running-stat updates; the
    layer wires MeanOut/VarianceOut back onto the same variables."""
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)
    layout = ctx.attr("data_layout", "NCHW")
    if layout == "NCHW":
        axes = tuple(i for i in range(X.ndim) if i != 1)
        shape = (1, -1) + (1,) * (X.ndim - 2)
    else:  # NHWC
        axes = tuple(range(X.ndim - 1))
        shape = (1,) * (X.ndim - 1) + (-1,)
    if is_test:
        mean, var = Mean, Variance
        saved_mean, saved_var = Mean, Variance
        mean_out, var_out = Mean, Variance
    else:
        x32 = X.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes)
        var = jnp.var(x32, axis=axes)
        saved_mean, saved_var = mean, var
        mean_out = momentum * Mean + (1.0 - momentum) * mean
        var_out = momentum * Variance + (1.0 - momentum) * var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    y = (X.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    y = y * Scale.reshape(shape) + Bias.reshape(shape)
    return {"Y": y.astype(X.dtype), "MeanOut": mean_out, "VarianceOut": var_out,
            "SavedMean": saved_mean, "SavedVariance": inv}


@register_op("layer_norm", propagate_seqlen=True)
def _layer_norm(ctx, X, Scale=None, Bias=None):
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, X.ndim))
    x32 = X.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.var(x32, axis=axes, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    bshape = (1,) * begin + X.shape[begin:]
    if Scale is not None:
        y = y * Scale.reshape(bshape)
    if Bias is not None:
        y = y + Bias.reshape(bshape)
    return {"Y": y.astype(X.dtype), "Mean": mean.reshape(X.shape[:begin]),
            "Variance": var.reshape(X.shape[:begin])}


@register_op("dropout", needs_rng=True)
def _dropout(ctx, X):
    p = ctx.attr("dropout_prob", 0.5)
    is_test = ctx.attr("is_test", False)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = X if impl == "upscale_in_train" else X * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones_like(X)}
    if p >= 1.0:
        # degenerate: drop everything (upscale would divide by zero)
        return {"Out": jnp.zeros_like(X), "Mask": jnp.zeros_like(X)}
    # Hot path: Pallas kernel with in-kernel TPU PRNG — XLA's counter-based
    # RNG is a long VPU integer chain that dominated transformer step time
    # (reference dropout_op.cu pays the same via cuRAND but on idle SMs).
    # The kernel's custom_vjp regenerates the mask from the seed, so no
    # mask tensor ever hits HBM.
    from . import pallas_dropout
    from .. import flags as _flags
    # Path choice (measured, docs/PERF.md): the Pallas kernel's in-kernel
    # PRNG made it the winner over threefry-fed XLA dropout, but it is a
    # fusion barrier — one extra read+write of the tensor fwd AND bwd.
    # With the counter-hash bits path (below) the XLA version fuses into
    # the surrounding chain at ~zero HBM cost, so "auto" prefers it; the
    # kernel stays selectable for A/B via FLAGS dropout_impl=pallas.
    impl_flag = _flags.get_flag("dropout_impl")
    if (impl_flag == "pallas"
            and impl == "upscale_in_train" and jax.default_backend() != "cpu"
            and pallas_dropout.supports(X, p)):
        seed = (jax.random.key_data(ctx.key).reshape(-1)[0]
                .astype(jnp.int32).reshape(1, 1))
        out = pallas_dropout.dropout_tpu(X, seed, float(p))
        # The true keep mask, regenerated from the same seed over a
        # never-zero input. It's an independent expression, so XLA DCEs
        # it when nothing consumes the Mask output (the backward doesn't:
        # the vjp re-derives the mask in-kernel).
        mask = (pallas_dropout.dropout_tpu(
            jnp.ones(X.shape, jnp.float32), seed, float(p)) != 0)
        return {"Out": out, "Mask": mask.astype(X.dtype)}
    # XLA fallback: uint8 bit-compare instead of bernoulli (bernoulli
    # materializes a full f32 uniform tensor; one random byte per element
    # decides keep at 1/256 resolution and fuses into the chain at a
    # quarter of the RNG traffic). custom_vjp regenerates the bits in the
    # backward so the mask is never stored as a residual.
    scale = 1.0 if impl != "upscale_in_train" else 1.0 / (1.0 - p)
    out = _bits_dropout(X, ctx.key, float(p), float(scale))
    # true keep mask from the same key; DCE'd when the Mask var is unused
    mask = _keep_bits(ctx.key, X.shape, float(p))
    return {"Out": out, "Mask": mask.astype(X.dtype)}


def _hash_bits8(key, shape):
    """One random byte per element from a counter hash: murmur3's fmix32
    avalanche over the element's linear index, seeded from the op's
    fold_in'd PRNG key. Dropout-grade randomness (the reference draws from
    cuRAND Philox, dropout_op.cu — also a counter hash, more rounds) at
    ~8 fused integer ops per element; jax.random.bits' threefry is a
    ~100-op unfused block chain that dominated the VPU cost of every
    dropout site it fed."""
    kd = jax.random.key_data(key).reshape(-1).astype(jnp.uint32)
    seed = kd[0] ^ (kd[-1] * np.uint32(0x9E3779B9))
    idx = jnp.zeros(shape, jnp.uint32)   # 0-d tensors: index 0
    stride = 1
    for d in range(len(shape) - 1, -1, -1):
        term = jax.lax.broadcasted_iota(jnp.uint32, shape, d)
        if stride != 1:
            term = term * np.uint32(stride)
        idx = idx + term
        stride *= int(shape[d])
    x = idx * np.uint32(2654435761) + seed
    x = (x ^ (x >> 16)) * np.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * np.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x & np.uint32(0xFF)).astype(jnp.uint8)


def _keep_bits(key, shape, p):
    t = round((1.0 - p) * 256) - 1
    if t < 0:                       # p ~ 1: nothing survives
        return jnp.zeros(shape, bool)
    return _hash_bits8(key, shape) <= np.uint8(min(255, t))


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _bits_dropout(x, key, p, scale):
    keep = _keep_bits(key, x.shape, p)
    return jnp.where(keep, x * jnp.asarray(scale, x.dtype), jnp.zeros_like(x))


def _bits_dropout_fwd(x, key, p, scale):
    return _bits_dropout(x, key, p, scale), key


def _bits_dropout_bwd(p, scale, key, dy):
    keep = _keep_bits(key, dy.shape, p)   # regenerated, not stored
    dx = jnp.where(keep, dy * jnp.asarray(scale, dy.dtype),
                   jnp.zeros_like(dy))
    dkey = np.zeros(jnp.shape(key), jax.dtypes.float0)
    return dx, dkey


_bits_dropout.defvjp(_bits_dropout_fwd, _bits_dropout_bwd)


@register_op("lrn", propagate_seqlen=False)
def _lrn(ctx, X):
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(X)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + X.shape[1]] for i in range(n))
    mid = jnp.power(k + alpha * acc, beta)
    return {"Out": X / mid, "MidOut": mid}


@register_op("im2sequence", propagate_seqlen=False)
def _im2sequence(ctx, X):
    kernels = _pair(ctx.attr("kernels"))
    strides = _pair(ctx.attr("strides", [1, 1]))
    pads = ctx.attr("paddings", [0, 0, 0, 0])
    n, c, h, w = X.shape
    xp = jnp.pad(X, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    patches = lax.conv_general_dilated_patches(
        xp, kernels, strides, "VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, OH, OW] -> [N, OH*OW, C*kh*kw]
    nn, ck, oh, ow = patches.shape
    out = patches.reshape(nn, ck, oh * ow).transpose(0, 2, 1)
    return {"Out": out.reshape(nn * oh * ow, ck)}


@register_op("grid_sampler", propagate_seqlen=False)
def _grid_sampler(ctx, X, Grid):
    """Bilinear grid sample (align_corners), NCHW."""
    n, c, h, w = X.shape
    gx = (Grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (Grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    wx = gx - x0; wy = gy - y0

    def sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        batch = jnp.arange(n)[:, None, None]
        return X[batch, :, yi, xi]  # [N, Hg, Wg, C]

    v00 = sample(x0, y0); v01 = sample(x0 + 1, y0)
    v10 = sample(x0, y0 + 1); v11 = sample(x0 + 1, y0 + 1)
    wx = wx[..., None]; wy = wy[..., None]
    out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
           + v10 * (1 - wx) * wy + v11 * wx * wy)
    return {"Output": out.transpose(0, 3, 1, 2)}
