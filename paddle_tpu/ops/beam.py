"""Beam-search op lowerings.

Capability parity with the reference's LoD beam search (reference:
paddle/fluid/operators/beam_search_op.cc, beam_search_decode_op.cc,
python/paddle/fluid/layers/nn.py beam_search :2657).

TPU-native redesign: the reference tracks variable-width beams in LoD
tensors and prunes finished hypotheses dynamically. Here beams have a static
width [B, beam] (standard TPU practice): finished beams are frozen by score
masking, decode runs a fixed max_len scan, and `beam_backtrack` gathers the
final sequences from the stacked (ids, parents) history — all static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

NEG_INF = -1e9


@register_op("tile_beam", propagate_seqlen=False)
def _tile_beam(ctx, X):
    """[B, ...] -> [B*beam, ...] repeating each row (beam-major compatible
    with reshape([B, beam, ...])). Repeats the @SEQLEN companion too."""
    k = ctx.attr("beam_size")
    out = jnp.repeat(X, k, axis=0)
    if ctx.env is not None and ctx.op is not None:
        from ..core.ir import SEQLEN_SUFFIX
        in_name = ctx.op.input("X")[0]
        comp = ctx.env.get(in_name + SEQLEN_SUFFIX)
        if comp is not None:
            for out_name in ctx.op.output("Out"):
                ctx.env[out_name + SEQLEN_SUFFIX] = jnp.repeat(comp, k, axis=0)
    return {"Out": out}


@register_op("beam_search_step", propagate_seqlen=False)
def _beam_search_step(ctx, LogProbs, AccScores, Finished):
    """One expansion step.

    LogProbs: [B, beam, V] log-softmax of the next token; AccScores:
    [B, beam]; Finished: [B, beam] (bool). Selects the global top `beam`
    continuations per batch row. Finished beams emit only end_id with
    unchanged score, so they survive unchanged (the reference keeps them in
    the LoD prune set).
    """
    beam = ctx.attr("beam_size")
    end_id = ctx.attr("end_id", 1)
    B, K, V = LogProbs.shape
    fin = Finished.astype(bool)

    # finished beams: ONLY the end_id continuation stays live (score += 0);
    # every other token must be -inf or a finished beam floods the top-k
    cont = jnp.where(fin[..., None], NEG_INF, LogProbs)
    end_col = jnp.full((B, K, V), NEG_INF, LogProbs.dtype).at[:, :, end_id].set(0.0)
    scores = AccScores[..., None] + jnp.where(fin[..., None], end_col, cont)

    flat = scores.reshape(B, K * V)
    top_scores, top_idx = lax.top_k(flat, beam)       # [B, beam]
    parent = (top_idx // V).astype(jnp.int32)
    token = (top_idx % V).astype(jnp.int32)
    parent_fin = jnp.take_along_axis(fin, parent, axis=1)
    new_fin = jnp.logical_or(parent_fin, token == end_id)
    return {"Ids": token, "Parents": parent, "AccScoresOut": top_scores,
            "FinishedOut": new_fin}


@register_op("beam_backtrack", propagate_seqlen=False)
def _beam_backtrack(ctx, Ids, Parents, AccScores):
    """Reconstruct sequences from stacked per-step selections
    (reference beam_search_decode_op.cc).

    Ids/Parents: [B, T, beam]; AccScores: [B, beam] final. Outputs
    SentenceIds [B, beam, T] (ranked best-first) + SentenceScores [B, beam].
    """
    B, T, K = Ids.shape

    def backstep(carry, t):
        beam_idx = carry                                     # [B, K]
        ids_t = jnp.take_along_axis(Ids[:, t], beam_idx, axis=1)
        parents_t = jnp.take_along_axis(Parents[:, t], beam_idx, axis=1)
        return parents_t, ids_t

    init = jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32)[None, :], (B, K))
    _, rev = lax.scan(backstep, init, jnp.arange(T - 1, -1, -1))
    seqs = jnp.flip(jnp.transpose(rev, (1, 2, 0)), axis=-1)  # [B, K, T]
    order = jnp.argsort(-AccScores, axis=1).astype(jnp.int32)
    seqs = jnp.take_along_axis(seqs, order[:, :, None], axis=1)
    scores = jnp.take_along_axis(AccScores, order, axis=1)
    return {"SentenceIds": seqs, "SentenceScores": scores}
