"""Sampled / structured losses: NCE, hierarchical sigmoid, linear-chain CRF,
CTC, edit distance.

Capability parity with reference ops (reference:
paddle/fluid/operators/nce_op.cc, hierarchical_sigmoid_op.cc (+
math/matrix_bit_code.*), linear_chain_crf_op.cc, crf_decoding_op.cc,
warpctc_op.cc, edit_distance_op.cc).

TPU-native redesign: everything is expressed as masked dense algebra and
`lax.scan` dynamic programs over padded [B, T, ...] batches — no LoD, no
per-sequence host loops, fully differentiable through the generic vjp path
(CRF/CTC recursions are log-space scans XLA maps onto the VPU/MXU).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op
from ..core import types


# ---------------------------------------------------------------------------
# NCE (noise-contrastive estimation)
# ---------------------------------------------------------------------------

@register_op("nce", needs_rng=True, propagate_seqlen=False)
def _nce(ctx, Input, Label, Weight, Bias=None, SampleWeight=None):
    """Input [B, D], Weight [V, D], Bias [V], Label [B, T_true].
    Uniform negative sampling (reference nce_op.cc sampler=uniform)."""
    num_neg = ctx.attr("num_neg_samples", 10)
    V = ctx.attr("num_total_classes", Weight.shape[0])
    B = Input.shape[0]
    label = Label.astype(jnp.int32)
    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]

    neg = jax.random.randint(ctx.key, (B, num_neg), 0, V)

    def logits_for(ids):
        w = jnp.take(Weight, ids, axis=0)            # [B, k, D]
        out = jnp.einsum("bd,bkd->bk", Input, w)
        if Bias is not None:
            out = out + jnp.take(Bias.reshape(-1), ids)
        return out

    true_logit = logits_for(label)                   # [B, T_true]
    neg_logit = logits_for(neg)                      # [B, num_neg]
    # NCE with uniform noise: P_n = 1/V
    log_pn = math.log(1.0 / V)
    true_cost = jax.nn.softplus(-(true_logit - (math.log(num_neg) + log_pn)))
    neg_cost = jax.nn.softplus(neg_logit - (math.log(num_neg) + log_pn))
    cost = jnp.sum(true_cost, axis=1) + jnp.sum(neg_cost, axis=1)
    if SampleWeight is not None:
        cost = cost * SampleWeight.reshape(-1)
    return {"Cost": cost[:, None],
            "SampleLogits": jnp.concatenate([true_logit, neg_logit], 1),
            "SampleLabels": jnp.concatenate([label, neg], 1)}


# ---------------------------------------------------------------------------
# Hierarchical sigmoid over a complete binary tree
# ---------------------------------------------------------------------------

def _bit_codes(label, num_classes):
    """Reference math/matrix_bit_code.h SimpleCode: node index starts at
    label + num_classes; path walks to the root of a complete binary tree."""
    depth = max(int(math.ceil(math.log2(max(num_classes, 2)))), 1)
    node = label + num_classes                      # [B]
    idxs, bits = [], []
    for _ in range(depth):
        bits.append((node & 1).astype(jnp.float32))
        node = node // 2
        idxs.append(node - 1)                        # internal node index
    # valid while node >= 1 (i.e. recorded index >= 0)
    idx = jnp.stack(idxs, axis=1)                    # [B, depth]
    bit = jnp.stack(bits, axis=1)
    valid = (idx >= 0).astype(jnp.float32)
    return jnp.maximum(idx, 0), bit, valid


@register_op("hierarchical_sigmoid", propagate_seqlen=False)
def _hsigmoid(ctx, X, W, Label, Bias=None):
    """X [B, D], W [num_classes-1, D], Bias [num_classes-1, 1]
    (reference hierarchical_sigmoid_op.cc)."""
    num_classes = ctx.attr("num_classes")
    label = Label.reshape(-1).astype(jnp.int32)
    idx, bit, valid = _bit_codes(label, num_classes)          # [B, depth]
    w = jnp.take(W, idx, axis=0)                              # [B, depth, D]
    logit = jnp.einsum("bd,bkd->bk", X, w)
    if Bias is not None:
        logit = logit + jnp.take(Bias.reshape(-1), idx)
    # sigmoid cross-entropy with the path bit as target
    loss = jax.nn.softplus(logit) - bit * logit
    cost = jnp.sum(loss * valid, axis=1, keepdims=True)
    return {"Out": cost, "PreOut": logit}


# ---------------------------------------------------------------------------
# Linear-chain CRF
# ---------------------------------------------------------------------------

@register_op("linear_chain_crf", propagate_seqlen=False)
def _linear_chain_crf(ctx, Emission, Transition, Label, SeqLen=None):
    """Emission [B, T, N]; Transition [N+2, N] (row 0: start, row 1: stop,
    rows 2..: pairwise w[from+2, to] — reference linear_chain_crf_op.h
    layout); Label [B, T(,1)]. Returns per-sequence negative log-likelihood.
    """
    if Label.ndim == 3:
        Label = Label[..., 0]
    label = Label.astype(jnp.int32)
    B, T, N = Emission.shape
    L = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    start, stop, trans = Transition[0], Transition[1], Transition[2:]
    e = Emission.astype(jnp.float32)
    mask = (jnp.arange(T)[None, :] < L[:, None]).astype(jnp.float32)

    # log partition: alpha recursion
    alpha0 = start[None, :] + e[:, 0]                         # [B, N]

    def alpha_step(alpha, t):
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + e[:, t]
        m = mask[:, t][:, None]
        return alpha * (1 - m) + nxt * m, None

    alpha, _ = lax.scan(alpha_step, alpha0, jnp.arange(1, T)) if T > 1 \
        else (alpha0, None)
    last_tag_logits = alpha + stop[None, :]
    log_z = jax.scipy.special.logsumexp(last_tag_logits, axis=1)

    # gold path score
    emit_score = jnp.sum(
        jnp.take_along_axis(e, label[..., None], axis=2)[..., 0] * mask, axis=1)
    prev, nxt = label[:, :-1], label[:, 1:]
    trans_score = jnp.sum(trans[prev, nxt] * mask[:, 1:], axis=1) if T > 1 \
        else jnp.zeros((B,))
    start_score = start[label[:, 0]]
    last_idx = jnp.maximum(L - 1, 0)
    last_tag = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    stop_score = stop[last_tag]
    gold = emit_score + trans_score + start_score + stop_score
    nll = (log_z - gold)[:, None]
    return {"LogLikelihood": nll, "Alpha": alpha,
            "EmissionExps": jnp.exp(e), "TransitionExps": jnp.exp(Transition)}


@register_op("crf_decoding", propagate_seqlen=False)
def _crf_decoding(ctx, Emission, Transition, Label=None, SeqLen=None):
    """Viterbi decode (reference crf_decoding_op.h). Output: best tag path
    [B, T] (padded region zeroed); with Label given, outputs mismatch mask
    like the reference."""
    B, T, N = Emission.shape
    L = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    start, stop, trans = Transition[0], Transition[1], Transition[2:]
    e = Emission.astype(jnp.float32)
    mask = (jnp.arange(T)[None, :] < L[:, None]).astype(jnp.float32)

    def vit_step(carry, t):
        score = carry                                       # [B, N]
        cand = score[:, :, None] + trans[None, :, :]        # [B, from, to]
        best_prev = jnp.argmax(cand, axis=1).astype(jnp.int32)
        nxt = jnp.max(cand, axis=1) + e[:, t]
        m = mask[:, t][:, None]
        score = score * (1 - m) + nxt * m
        return score, best_prev

    score0 = start[None, :] + e[:, 0]
    score, back = lax.scan(vit_step, score0, jnp.arange(1, T)) if T > 1 \
        else (score0, jnp.zeros((0, B, N), jnp.int32))
    final = score + stop[None, :]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)  # [B]

    def backtrack(tag, t_rev):
        bp = back[t_rev]                                    # [B, N]
        prev_tag = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        in_range = (t_rev + 1 <= (L - 1)).astype(jnp.int32)
        new_tag = prev_tag * in_range + tag * (1 - in_range)
        return new_tag, new_tag

    if T > 1:
        _, rev_tags = lax.scan(backtrack, last_tag, jnp.arange(T - 2, -1, -1))
        path = jnp.concatenate(
            [jnp.flip(jnp.swapaxes(rev_tags, 0, 1), axis=1),
             last_tag[:, None]], axis=1)
    else:
        path = last_tag[:, None]
    path = (path * mask.astype(jnp.int32))
    out = {"ViterbiPath": path.astype(types.index_dtype())}
    if Label is not None:
        lbl = Label[..., 0] if Label.ndim == 3 else Label
        out["ViterbiPath"] = ((path != lbl.astype(jnp.int32)) *
                              mask.astype(jnp.int32)).astype(types.index_dtype())
    return out


# ---------------------------------------------------------------------------
# CTC loss (reference warpctc_op.cc)
# ---------------------------------------------------------------------------

@register_op("warpctc", propagate_seqlen=False)
def _warpctc(ctx, Logits, Label, LogitsLen=None, LabelLen=None):
    """Logits [B, T, C] (blank = attr), Label [B, U] int; returns per-seq
    CTC loss. Standard alpha recursion in log space over an extended label
    sequence with interleaved blanks — a lax.scan DP."""
    blank = ctx.attr("blank", 0)
    B, T, C = Logits.shape
    U = Label.shape[1]
    label = Label.astype(jnp.int32)
    t_len = LogitsLen.reshape(-1).astype(jnp.int32) if LogitsLen is not None \
        else jnp.full((B,), T, jnp.int32)
    u_len = LabelLen.reshape(-1).astype(jnp.int32) if LabelLen is not None \
        else jnp.full((B,), U, jnp.int32)

    logp = jax.nn.log_softmax(Logits.astype(jnp.float32), axis=-1)
    S = 2 * U + 1
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    ext_valid = jnp.arange(S)[None, :] < (2 * u_len + 1)[:, None]

    NEG = -1e30
    # can skip from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    can_skip = jnp.zeros((B, S), bool)
    can_skip = can_skip.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(logp[:, 0], ext[:, 1][:, None], axis=1)[:, 0])
    alpha0 = jnp.where(ext_valid, alpha0, NEG)

    def lse(a, b):
        return jnp.logaddexp(a, b)

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        tot = lse(lse(stay, prev1), prev2)
        emit = jnp.take_along_axis(logp[:, t], ext, axis=1)
        new = jnp.where(ext_valid, tot + emit, NEG)
        active = (t < t_len)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    lastS = 2 * u_len                                  # final blank position
    a_last = jnp.take_along_axis(alpha, lastS[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.maximum(lastS - 1, 0)[:, None],
                                 axis=1)[:, 0]
    # empty label rows (u_len == 0) have only the all-blank path — the
    # clamped lastS-1 would double-count it
    a_prev = jnp.where(u_len > 0, a_prev, NEG)
    ll = jnp.logaddexp(a_last, a_prev)
    return {"Loss": (-ll)[:, None]}


# ---------------------------------------------------------------------------
# Edit distance (reference edit_distance_op.cc)
# ---------------------------------------------------------------------------

@register_op("edit_distance", propagate_seqlen=False)
def _edit_distance(ctx, Hyps, Refs, HypsLen=None, RefsLen=None):
    """Levenshtein distance per row between padded int sequences."""
    normalized = ctx.attr("normalized", False)
    hyp = Hyps[..., 0] if Hyps.ndim == 3 else Hyps
    ref = Refs[..., 0] if Refs.ndim == 3 else Refs
    B, Th = hyp.shape
    Tr = ref.shape[1]
    hl = HypsLen.reshape(-1).astype(jnp.int32) if HypsLen is not None \
        else jnp.full((B,), Th, jnp.int32)
    rl = RefsLen.reshape(-1).astype(jnp.int32) if RefsLen is not None \
        else jnp.full((B,), Tr, jnp.int32)

    BIG = jnp.float32(1e9)
    row0 = jnp.broadcast_to(jnp.arange(Tr + 1, dtype=jnp.float32)[None, :],
                            (B, Tr + 1))
    row0 = jnp.minimum(row0, rl[:, None].astype(jnp.float32))  # clamp beyond len

    def dp_row(prev, i):
        # computing row i (1-indexed over hyp)
        sub_cost = (hyp[:, i - 1][:, None] != ref).astype(jnp.float32)
        # build current row with a scan over columns via associative trick:
        # standard levenshtein needs sequential column dependency; do a scan.
        def col_step(left, j):
            up = prev[:, j]
            diag = prev[:, j - 1]
            cur = jnp.minimum(jnp.minimum(up + 1, left + 1),
                              diag + sub_cost[:, j - 1])
            # beyond ref length: keep value of the length column
            valid = (j <= rl).astype(jnp.float32)
            cur = cur * valid + left * (1 - valid)
            return cur, cur

        first = prev[:, 0] + 1
        _, cols = lax.scan(col_step, first, jnp.arange(1, Tr + 1))
        row = jnp.concatenate([first[:, None], jnp.swapaxes(cols, 0, 1)], 1)
        active = (i <= hl)[:, None].astype(jnp.float32)
        return prev * (1 - active) + row * active, None

    final, _ = lax.scan(dp_row, row0, jnp.arange(1, Th + 1))
    dist = jnp.take_along_axis(final, rl[:, None], axis=1)[:, 0]
    if normalized:
        dist = dist / jnp.maximum(rl.astype(jnp.float32), 1.0)
    return {"Out": dist[:, None], "SequenceNum": jnp.array([B], types.index_dtype())}
