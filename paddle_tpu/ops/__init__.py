"""Op library: importing this package registers every lowering rule."""

from . import math  # noqa: F401
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import rnn  # noqa: F401
from . import sequence  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control  # noqa: F401
from . import tensor_array  # noqa: F401
from . import detection  # noqa: F401
from . import quantize  # noqa: F401
from . import beam  # noqa: F401
from . import loss_extra  # noqa: F401
from . import pallas_attention  # noqa: F401
from . import paged_attention  # noqa: F401
from . import extra_nn  # noqa: F401
