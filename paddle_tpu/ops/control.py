"""Control-flow op lowerings: while / static_rnn / conditional_block.

Capability parity with the reference's control ops (reference:
paddle/fluid/operators/while_op.cc:36, recurrent_op.cc:222 (StepScopes :53),
conditional_block_op.cc; python DSL python/paddle/fluid/layers/
control_flow.py: While :654, StaticRNN :429, ConditionalBlock :1200).

TPU-native redesign: the reference runs sub-blocks through a nested Executor
with per-step scopes. Here a sub-block lowers to a pure function over its
carried variables and becomes the body of `lax.while_loop` / `lax.scan` /
`lax.cond` — no data-dependent Python control flow inside the compiled step,
so the whole loop stays on-device with static shapes (XLA requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _run_sub(lowerer, sub_idx, base_env, carry, key):
    env2 = dict(base_env)
    env2.update(carry)
    lowerer.run_block(sub_idx, env2, key)
    return env2


@register_op("while", propagate_seqlen=False, needs_rng=True)
def _while(ctx, X=None, Condition=None):
    """attrs: sub_block (block idx), carry_vars (loop-state names incl. the
    condition var). The sub-block must write the condition each iteration."""
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    carry_names = list(ctx.attr("carry_vars"))
    cond_name = ctx.attr("cond_var")
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    init_carry = {n: env[n] for n in carry_names}
    init_carry["__loop_t__"] = jnp.int32(0)

    def cond_fn(carry):
        return carry[cond_name].reshape(())

    def body_fn(carry):
        t = carry.pop("__loop_t__")
        # distinct randomness per iteration for RNG ops in the body
        step_key = jax.random.fold_in(key, t)
        env2 = _run_sub(lowerer, sub_idx, env, carry, step_key)
        out = {n: env2[n] for n in carry_names}
        out["__loop_t__"] = t + 1
        return out

    final = lax.while_loop(cond_fn, body_fn, init_carry)
    return {"Out": [final[n] for n in carry_names]}


@register_op("static_rnn", propagate_seqlen=False, needs_rng=True)
def _static_rnn(ctx, X=None):
    """Scan a sub-block over the time axis.

    attrs: sub_block; step_inputs [(outer_name, inner_name), ...] where outer
    is [B, T, ...] sliced to [B, ...] per step; memories
    [(inner_pre_name, inner_mem_name, init_name), ...] (reference StaticRNN
    memory/update_memory); step_outputs [inner_name, ...] stacked to
    [B, T, ...].
    """
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    step_inputs = [tuple(p) for p in ctx.attr("step_inputs")]
    memories = [tuple(m) for m in ctx.attr("memories")]
    step_outputs = list(ctx.attr("step_outputs"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    xs = {inner: jnp.swapaxes(env[outer], 0, 1)  # [T, B, ...]
          for outer, inner in step_inputs}
    init_mems = {pre: env[init] for pre, mem, init in memories}
    init_mems["__loop_t__"] = jnp.int32(0)

    def body(carry, xt):
        t = carry.pop("__loop_t__")
        carry_in = dict(carry)
        if xt is not None:
            carry_in.update(xt)
        step_key = jax.random.fold_in(key, t)  # fresh RNG per timestep
        env2 = _run_sub(lowerer, sub_idx, env, carry_in, step_key)
        new_carry = {pre: env2[mem] for pre, mem, init in memories}
        new_carry["__loop_t__"] = t + 1
        outs = tuple(env2[n] for n in step_outputs)
        return new_carry, outs

    if xs:
        _, stacked = lax.scan(body, init_mems, xs)
    else:  # input-free decode loop: length from attr
        n = int(ctx.attr("num_steps") or 0)
        if n <= 0:
            raise ValueError(
                "StaticRNN has no step_input and no positive num_steps — "
                "pass StaticRNN(num_steps=...) for input-free decode loops")
        _, stacked = lax.scan(body, init_mems, None, length=n)
    # stacked outputs come back [T, B, ...] -> [B, T, ...]
    return {"Out": [jnp.swapaxes(s, 0, 1) for s in stacked]}


@register_op("conditional_block", propagate_seqlen=False, needs_rng=True)
def _conditional_block(ctx, Cond, X=None):
    """attrs: sub_block, out_vars (written by the branch), else_block
    (optional). Lowered to lax.cond; with no else branch the false path
    returns the vars' current values (reference conditional_block_op.cc
    skips the block). The layer declares prior out-var values + sub-block
    externals under X so the executor materializes them in env."""
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    else_idx = ctx.attr("else_block", -1)
    out_names = list(ctx.attr("out_vars"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    pred = Cond.reshape(()) if hasattr(Cond, "reshape") else Cond

    def true_fn(_):
        env2 = _run_sub(lowerer, sub_idx, env, {}, key)
        return tuple(env2[n] for n in out_names)

    def false_fn(_):
        if else_idx >= 0:
            env2 = _run_sub(lowerer, else_idx, env, {}, key)
            return tuple(env2[n] for n in out_names)
        missing = [n for n in out_names if n not in env]
        if missing:
            raise ValueError(
                f"conditional_block out_vars {missing} have no prior value; "
                f"assign them before the block or add an else branch")
        return tuple(env[n] for n in out_names)

    outs = lax.cond(pred, true_fn, false_fn, None)
    return {"Out": list(outs)}


@register_op("select_input", propagate_seqlen=False)
def _select_input(ctx, X, Mask):
    """Mask-select between branch results (IfElse merge)."""
    xs = X if isinstance(X, list) else [X]
    idx = Mask.reshape(()).astype(jnp.int32)
    return {"Out": lax.switch(idx, [lambda x=x: x for x in xs])}
