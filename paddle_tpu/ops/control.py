"""Control-flow op lowerings: while / static_rnn / conditional_block.

Capability parity with the reference's control ops (reference:
paddle/fluid/operators/while_op.cc:36, recurrent_op.cc:222 (StepScopes :53),
conditional_block_op.cc; python DSL python/paddle/fluid/layers/
control_flow.py: While :654, StaticRNN :429, ConditionalBlock :1200).

TPU-native redesign: the reference runs sub-blocks through a nested Executor
with per-step scopes. Here a sub-block lowers to a pure function over its
carried variables and becomes the body of `lax.while_loop` / `lax.scan` /
`lax.cond` — no data-dependent Python control flow inside the compiled step,
so the whole loop stays on-device with static shapes (XLA requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _run_sub(lowerer, sub_idx, base_env, carry, key):
    env2 = dict(base_env)
    env2.update(carry)
    lowerer.run_block(sub_idx, env2, key)
    return env2


@register_op("while", propagate_seqlen=False, needs_rng=True)
def _while(ctx, X=None, Condition=None):
    """attrs: sub_block (block idx), carry_vars (loop-state names incl. the
    condition var). The sub-block must write the condition each iteration."""
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    carry_names = list(ctx.attr("carry_vars"))
    cond_name = ctx.attr("cond_var")
    pre_map = ctx.attr("carry_pre", {}) or {}
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    init_carry = {n: env[pre_map.get(n, n)] for n in carry_names}
    init_carry["__loop_t__"] = jnp.int32(0)

    def cond_fn(carry):
        return carry[cond_name].reshape(())

    def body_fn(carry):
        t = carry.pop("__loop_t__")
        # distinct randomness per iteration for RNG ops in the body
        step_key = jax.random.fold_in(key, t)
        env2 = _run_sub(lowerer, sub_idx, env, carry, step_key)
        out = {n: env2[n] for n in carry_names}
        out["__loop_t__"] = t + 1
        return out

    final = lax.while_loop(cond_fn, body_fn, init_carry)
    return {"Out": [final[n] for n in carry_names]}


@register_op("static_rnn", propagate_seqlen=False, needs_rng=True)
def _static_rnn(ctx, X=None):
    """Scan a sub-block over the time axis.

    attrs: sub_block; step_inputs [(outer_name, inner_name), ...] where outer
    is [B, T, ...] sliced to [B, ...] per step; memories
    [(inner_pre_name, inner_mem_name, init_name), ...] (reference StaticRNN
    memory/update_memory); step_outputs [inner_name, ...] stacked to
    [B, T, ...].
    """
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    step_inputs = [tuple(p) for p in ctx.attr("step_inputs")]
    memories = [tuple(m) for m in ctx.attr("memories")]
    step_outputs = list(ctx.attr("step_outputs"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    xs = {inner: jnp.swapaxes(env[outer], 0, 1)  # [T, B, ...]
          for outer, inner in step_inputs}
    init_mems = {pre: env[init] for pre, mem, init in memories}
    init_mems["__loop_t__"] = jnp.int32(0)

    def body(carry, xt):
        t = carry.pop("__loop_t__")
        carry_in = dict(carry)
        if xt is not None:
            carry_in.update(xt)
        step_key = jax.random.fold_in(key, t)  # fresh RNG per timestep
        env2 = _run_sub(lowerer, sub_idx, env, carry_in, step_key)
        new_carry = {pre: env2[mem] for pre, mem, init in memories}
        new_carry["__loop_t__"] = t + 1
        outs = tuple(env2[n] for n in step_outputs)
        return new_carry, outs

    if xs:
        _, stacked = lax.scan(body, init_mems, xs)
    else:  # input-free decode loop: length from attr
        n = int(ctx.attr("num_steps") or 0)
        if n <= 0:
            raise ValueError(
                "StaticRNN has no step_input and no positive num_steps — "
                "pass StaticRNN(num_steps=...) for input-free decode loops")
        _, stacked = lax.scan(body, init_mems, None, length=n)
    # stacked outputs come back [T, B, ...] -> [B, T, ...]
    return {"Out": [jnp.swapaxes(s, 0, 1) for s in stacked]}


@register_op("conditional_block", propagate_seqlen=False, needs_rng=True)
def _conditional_block(ctx, Cond, X=None):
    """attrs: sub_block, out_vars (written by the branch), else_block
    (optional). Lowered to lax.cond; with no else branch the false path
    returns the vars' current values (reference conditional_block_op.cc
    skips the block). The layer declares prior out-var values + sub-block
    externals under X so the executor materializes them in env."""
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    else_idx = ctx.attr("else_block", -1)
    out_names = list(ctx.attr("out_vars"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    pred = Cond.reshape(()) if hasattr(Cond, "reshape") else Cond

    def true_fn(_):
        env2 = _run_sub(lowerer, sub_idx, env, {}, key)
        return tuple(env2[n] for n in out_names)

    def false_fn(_):
        if else_idx >= 0:
            env2 = _run_sub(lowerer, else_idx, env, {}, key)
            return tuple(env2[n] for n in out_names)
        missing = [n for n in out_names if n not in env]
        if missing:
            raise ValueError(
                f"conditional_block out_vars {missing} have no prior value; "
                f"assign them before the block or add an else branch")
        return tuple(env[n] for n in out_names)

    outs = lax.cond(pred, true_fn, false_fn, None)
    return {"Out": list(outs)}


@register_op("bounded_while", propagate_seqlen=False, needs_rng=True)
def _bounded_while(ctx, X=None, Condition=None):
    """Differentiable while: a `While(cond, max_iters=N)` loop lowered to a
    fixed-length lax.scan with a per-iteration done-mask, because
    lax.while_loop has no reverse-mode derivative. Iterations after the
    condition turns false keep the carry unchanged, so the numerics match the
    dynamic loop exactly while staying reverse-differentiable (the reference's
    while_grad runs the sub-block backward with step scopes,
    while_op.cc:96 — here jax.vjp through the scan delivers the same grads).
    """
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    carry_names = list(ctx.attr("carry_vars"))
    cond_name = ctx.attr("cond_var")
    pre_map = ctx.attr("carry_pre", {}) or {}
    n_iters = int(ctx.attr("max_iters"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    init_carry = {n: env[pre_map.get(n, n)] for n in carry_names}

    def body(carry, t):
        live = carry[cond_name].reshape(())
        step_key = jax.random.fold_in(key, t)
        env2 = _run_sub(lowerer, sub_idx, env, dict(carry), step_key)
        out = {n: jnp.where(live, env2[n], carry[n]) for n in carry_names}
        return out, None

    final, _ = lax.scan(body, init_carry, jnp.arange(n_iters))
    return {"Out": [final[n] for n in carry_names]}


@register_op("dynamic_rnn", propagate_seqlen=False, needs_rng=True)
def _dynamic_rnn(ctx, X=None, SeqLen=None):
    """Variable-length RNN over padded batches (reference DynamicRNN,
    python/paddle/fluid/layers/control_flow.py:1538, lowered there to
    lod_rank_table + lod_tensor_to_array + while + shrink_rnn_memory).

    TPU-native redesign: one lax.scan over the time axis with per-row
    masking — a row's memory freezes once t >= its length (the masked-update
    equivalent of shrink_rnn_memory's physical batch shrink), and step
    outputs are zeroed past the row's length, so the stacked output matches
    the reference's LoD output and `sequence_pool('last')` recovers each
    row's final state. attrs mirror static_rnn plus the lengths input.
    """
    lowerer = ctx.lowerer
    env = ctx.env
    sub_idx = ctx.attr("sub_block")
    step_inputs = [tuple(p) for p in ctx.attr("step_inputs")]
    memories = [tuple(m) for m in ctx.attr("memories")]
    step_outputs = list(ctx.attr("step_outputs"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    first_outer = step_inputs[0][0]
    x0 = env[first_outer]
    B, T = x0.shape[0], x0.shape[1]
    lengths = (SeqLen.reshape(-1) if SeqLen is not None
               else jnp.full((B,), T, jnp.int32))

    xs = {inner: jnp.swapaxes(env[outer], 0, 1)  # [T, B, ...]
          for outer, inner in step_inputs}
    init_mems = {pre: env[init] for pre, mem, init in memories}
    init_mems["__loop_t__"] = jnp.int32(0)

    def _row_mask(active, v):
        # boolean select (NOT arithmetic x*m): padded timesteps may compute
        # NaN/Inf (div/log over garbage), and 0*NaN would poison the output
        m = active
        while m.ndim < v.ndim:
            m = m[..., None]
        return m

    def body(carry, xt):
        t = carry.pop("__loop_t__")
        active = lengths > t                       # [B]
        carry_in = dict(carry)
        carry_in.update(xt)
        step_key = jax.random.fold_in(key, t)
        env2 = _run_sub(lowerer, sub_idx, env, carry_in, step_key)
        new_carry = {}
        for pre, mem, init in memories:
            old, new = carry[pre], env2[mem]
            new_carry[pre] = jnp.where(_row_mask(active, new), new, old)
        new_carry["__loop_t__"] = t + 1
        outs = tuple(jnp.where(_row_mask(active, env2[n]), env2[n],
                               jnp.zeros((), env2[n].dtype))
                     for n in step_outputs)
        return new_carry, outs

    _, stacked = lax.scan(body, init_mems, xs)
    return {"Out": [jnp.swapaxes(s, 0, 1) for s in stacked],
            "OutLen": [lengths.astype(jnp.int32)] * len(step_outputs)}


@register_op("if_else", propagate_seqlen=False, needs_rng=True)
def _if_else(ctx, Cond, X=None):
    """Per-row conditional (reference IfElse, control_flow.py:1408): the
    reference physically splits the batch by the [B,1] bool mask, runs each
    sub-block on its rows, and merges. TPU-native redesign: both branches run
    on the FULL batch (SPMD-friendly, no dynamic shapes) and outputs are
    merged row-wise with `where` — identical results for row-local compute,
    which is what the reference API supports.
    attrs: true_block, false_block, true_outs, false_outs (inner names)."""
    lowerer = ctx.lowerer
    env = ctx.env
    true_idx = ctx.attr("true_block")
    false_idx = ctx.attr("false_block")
    true_outs = list(ctx.attr("true_outs"))
    false_outs = list(ctx.attr("false_outs"))
    key = ctx.key if ctx.key is not None else jax.random.key(0)

    env_t = _run_sub(lowerer, true_idx, env, {}, key)
    env_f = _run_sub(lowerer, false_idx, env, {}, key)
    cond = Cond.reshape(Cond.shape[0])            # [B]
    merged = []
    for tn, fn in zip(true_outs, false_outs):
        tv, fv = env_t[tn], env_f[fn]
        c = cond
        while c.ndim < tv.ndim:
            c = c[..., None]
        merged.append(jnp.where(c, tv, fv.astype(tv.dtype)))
    return {"Out": merged}


@register_op("select_input", propagate_seqlen=False)
def _select_input(ctx, X, Mask):
    """Mask-select between branch results (IfElse merge)."""
    xs = X if isinstance(X, list) else [X]
    idx = Mask.reshape(()).astype(jnp.int32)
    return {"Out": lax.switch(idx, [lambda x=x: x for x in xs])}
