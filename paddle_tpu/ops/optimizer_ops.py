"""Optimizer update ops.

Capability parity with the reference's optimizer op kernels (reference:
paddle/fluid/operators/{sgd,momentum,adam,adamax,adagrad,decayed_adagrad,
adadelta,rmsprop,ftrl,proximal_gd,proximal_adagrad}_op.cc).

Each update is a pure rule `new_state = f(param, grad, state, lr)`; the
executor writes outputs back onto the same persistable variables and donates
their buffers to XLA, so updates are in-place in HBM and fuse into the step.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lr(LearningRate):
    return LearningRate.reshape(()) if hasattr(LearningRate, "reshape") else LearningRate


@register_op("sgd", propagate_seqlen=False)
def _sgd(ctx, Param, Grad, LearningRate):
    return {"ParamOut": Param - _lr(LearningRate) * Grad.astype(Param.dtype)}


@register_op("momentum", propagate_seqlen=False)
def _momentum(ctx, Param, Grad, Velocity, LearningRate):
    mu = ctx.attr("mu", 0.9)
    lr = _lr(LearningRate)
    v = mu * Velocity + Grad
    if ctx.attr("use_nesterov", False):
        p = Param - (Grad + mu * v) * lr
    else:
        p = Param - lr * v
    return {"ParamOut": p, "VelocityOut": v}


@register_op("adam", propagate_seqlen=False)
def _adam(ctx, Param, Grad, Moment1, Moment2, Beta1Pow, Beta2Pow, LearningRate):
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(LearningRate)
    m1 = b1 * Moment1 + (1 - b1) * Grad
    m2 = b2 * Moment2 + (1 - b2) * Grad * Grad
    lr_t = lr * jnp.sqrt(1 - Beta2Pow.reshape(())) / (1 - Beta1Pow.reshape(()))
    p = Param - lr_t * m1 / (jnp.sqrt(m2) + eps)
    return {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
            "Beta1PowOut": Beta1Pow * b1, "Beta2PowOut": Beta2Pow * b2}


@register_op("adamax", propagate_seqlen=False)
def _adamax(ctx, Param, Grad, Moment, InfNorm, Beta1Pow, LearningRate):
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(LearningRate)
    m = b1 * Moment + (1 - b1) * Grad
    u = jnp.maximum(b2 * InfNorm, jnp.abs(Grad))
    p = Param - (lr / (1 - Beta1Pow.reshape(()))) * m / (u + eps)
    return {"ParamOut": p, "MomentOut": m, "InfNormOut": u,
            "Beta1PowOut": Beta1Pow * b1}


@register_op("adagrad", propagate_seqlen=False)
def _adagrad(ctx, Param, Grad, Moment, LearningRate):
    eps = ctx.attr("epsilon", 1e-6)
    m = Moment + Grad * Grad
    p = Param - _lr(LearningRate) * Grad / (jnp.sqrt(m) + eps)
    return {"ParamOut": p, "MomentOut": m}


@register_op("decayed_adagrad", propagate_seqlen=False)
def _decayed_adagrad(ctx, Param, Grad, Moment, LearningRate):
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m = decay * Moment + (1 - decay) * Grad * Grad
    p = Param - _lr(LearningRate) * Grad / (jnp.sqrt(m) + eps)
    return {"ParamOut": p, "MomentOut": m}


@register_op("adadelta", propagate_seqlen=False)
def _adadelta(ctx, Param, Grad, AvgSquaredGrad, AvgSquaredUpdate):
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    g2 = rho * AvgSquaredGrad + (1 - rho) * Grad * Grad
    update = -jnp.sqrt((AvgSquaredUpdate + eps) / (g2 + eps)) * Grad
    u2 = rho * AvgSquaredUpdate + (1 - rho) * update * update
    return {"ParamOut": Param + update, "AvgSquaredGradOut": g2,
            "AvgSquaredUpdateOut": u2}


@register_op("rmsprop", propagate_seqlen=False)
def _rmsprop(ctx, Param, Grad, MeanSquare, Moment, LearningRate, MeanGrad=None):
    rho = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    mu = ctx.attr("momentum", 0.0)
    lr = _lr(LearningRate)
    ms = rho * MeanSquare + (1 - rho) * Grad * Grad
    if ctx.attr("centered", False) and MeanGrad is not None:
        mg = rho * MeanGrad + (1 - rho) * Grad
        denom = lax.rsqrt(ms - mg * mg + eps)
        mom = mu * Moment + lr * Grad * denom
        return {"ParamOut": Param - mom, "MeanSquareOut": ms, "MomentOut": mom,
                "MeanGradOut": mg}
    mom = mu * Moment + lr * Grad * lax.rsqrt(ms + eps)
    return {"ParamOut": Param - mom, "MeanSquareOut": ms, "MomentOut": mom}


@register_op("ftrl", propagate_seqlen=False)
def _ftrl(ctx, Param, Grad, SquaredAccumulator, LinearAccumulator, LearningRate):
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(LearningRate)
    new_sq = SquaredAccumulator + Grad * Grad
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(SquaredAccumulator)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(SquaredAccumulator, -lr_power)) / lr
    lin = LinearAccumulator + Grad - sigma * Param
    if lr_power == -0.5:
        x = -lin
        y = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        x = -lin
        y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (x + jnp.sign(lin) * l1) / y
    p = jnp.where(jnp.abs(lin) > l1, pre_shrink, 0.0)
    return {"ParamOut": p, "SquaredAccumOut": new_sq, "LinearAccumOut": lin}


@register_op("proximal_gd", propagate_seqlen=False)
def _proximal_gd(ctx, Param, Grad, LearningRate):
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr = _lr(LearningRate)
    prox = Param - lr * Grad
    p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": p}


@register_op("proximal_adagrad", propagate_seqlen=False)
def _proximal_adagrad(ctx, Param, Grad, Moment, LearningRate):
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m = Moment + Grad * Grad
    lr = _lr(LearningRate) / jnp.sqrt(m + 1e-12)
    prox = Param - lr * Grad
    p = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": p, "MomentOut": m}


@register_op("average_accumulates", propagate_seqlen=False)
def _average_accumulates(ctx, param, in_sum_1, in_sum_2, in_sum_3,
                         in_num_accumulates, in_old_num_accumulates,
                         in_num_updates):
    """Sliding-window parameter-sum maintenance for ModelAverage
    (reference average_accumulates_op.h:44-135). Three-tier summation
    avoids precision loss: sum_1 rolls into sum_2 every 16384 updates;
    when the window exceeds min(max_average_window, num_updates *
    average_window) everything rolls into sum_3 and the counters reset.
    Branches become selects — the counters are scalars, so this costs
    nothing next to the parameter-sized adds."""
    avg_win = float(ctx.attr("average_window", 0.0))
    max_win = int(ctx.attr("max_average_window", 10000))
    min_win = int(ctx.attr("min_average_window", 10000))
    k_max = 16384  # kMaxNumAccumulates

    cdtype = in_num_updates.dtype
    num_updates = in_num_updates + 1
    num_acc = in_num_accumulates + 1
    nu = num_updates.reshape(())
    na = num_acc.reshape(())

    s1 = in_sum_1 + param
    roll = (nu % k_max) == 0
    s2 = jnp.where(roll, in_sum_2 + s1, in_sum_2)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)

    # window threshold: min(max_win, int(num_updates * average_window)),
    # matching the reference's std::min<int64_t> truncation
    win = jnp.minimum(jnp.asarray(max_win, cdtype),
                      (nu.astype(jnp.float32) * avg_win).astype(cdtype))
    trigger = (na >= min_win) & (na >= win)
    s3 = jnp.where(trigger, s1 + s2, in_sum_3)
    s1 = jnp.where(trigger, jnp.zeros_like(s1), s1)
    s2 = jnp.where(trigger, jnp.zeros_like(s2), s2)
    old = jnp.where(trigger, num_acc, in_old_num_accumulates)
    num_acc = jnp.where(trigger, jnp.zeros_like(num_acc), num_acc)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": num_acc,
            "out_old_num_accumulates": old,
            "out_num_updates": num_updates}
