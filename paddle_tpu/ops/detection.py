"""Detection op family (SSD/RPN support).

Capability parity with reference paddle/fluid/operators/detection/ (3.5k
LoC): prior_box_op.h:57, anchor_generator_op.h, iou_similarity_op.h,
box_coder_op.h:40 (encode/decode center-size), bipartite_match_op.cc,
target_assign_op.h, multiclass_nms_op.cc, mine_hard_examples_op.cc,
polygon_box_transform_op.cc, rpn_target_assign_op.cc.

TPU-native redesign decisions:
- The reference emits LoD outputs with data-dependent row counts
  (multiclass_nms keeps a variable number of detections; mine_hard_examples
  emits a variable negative set). XLA needs static shapes, so such ops
  return FIXED-size outputs with a validity convention: detections are
  [B, keep_top_k, 6] padded with label=-1 plus an explicit count [B];
  hard-example mining returns a [B, M] negative MASK instead of an index
  list. Downstream in-graph consumers (ssd_loss) use the masks; host code
  can compact with the counts.
- Greedy/sequential algorithms (bipartite matching, NMS suppression) are
  bounded lax.fori_loop's over static extents, vmapped over the batch —
  the loops stay on-device.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


# ---------------------------------------------------------------------------
# priors / anchors
# ---------------------------------------------------------------------------

def _expand_aspect_ratios(aspect_ratios, flip):
    """reference prior_box_op.h ExpandAspectRatios: dedup, keep 1.0 first,
    add flipped ratios."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


@register_op("prior_box", propagate_seqlen=False)
def _prior_box(ctx, Input, Image):
    """SSD priors over a feature map (reference prior_box_op.h:57).
    Outputs Boxes/Variances [H, W, num_priors, 4] in normalized ltrb."""
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    flip = ctx.attr("flip", False)
    ars = _expand_aspect_ratios(ctx.attr("aspect_ratios", [1.0]), flip)
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    clip = ctx.attr("clip", False)
    offset = ctx.attr("offset", 0.5)
    img_h, img_w = Image.shape[2], Image.shape[3]
    feat_h, feat_w = Input.shape[2], Input.shape[3]
    step_w = ctx.attr("step_w", 0.0) or img_w / feat_w
    step_h = ctx.attr("step_h", 0.0) or img_h / feat_h

    # per-cell prior (w, h) list in pixels. Default reference ordering
    # (prior_box_op.h else-branch): per min_size all aspect ratios (ar=1
    # first) then the sqrt(min*max) square; with
    # min_max_aspect_ratios_order=True (:96): min, max-square, then the
    # non-1 aspect ratios — weight-compatible with reference SSD heads.
    mm_order = ctx.attr("min_max_aspect_ratios_order", False)
    wh = []
    for s, mins in enumerate(min_sizes):
        if mm_order:
            wh.append((mins, mins))
            if max_sizes:
                m = math.sqrt(mins * max_sizes[s])
                wh.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                wh.append((mins * math.sqrt(ar), mins / math.sqrt(ar)))
        else:
            for ar in ars:
                wh.append((mins * math.sqrt(ar), mins / math.sqrt(ar)))
            if max_sizes:
                m = math.sqrt(mins * max_sizes[s])
                wh.append((m, m))
    wh = jnp.asarray(wh, jnp.float32)                     # [P, 2]

    cx = (jnp.arange(feat_w) + offset) * step_w           # [W]
    cy = (jnp.arange(feat_h) + offset) * step_h           # [H]
    cx = jnp.broadcast_to(cx[None, :, None], (feat_h, feat_w, wh.shape[0]))
    cy = jnp.broadcast_to(cy[:, None, None], (feat_h, feat_w, wh.shape[0]))
    half_w = wh[None, None, :, 0] / 2.0
    half_h = wh[None, None, :, 1] / 2.0
    boxes = jnp.stack([(cx - half_w) / img_w, (cy - half_h) / img_h,
                       (cx + half_w) / img_w, (cy + half_h) / img_h], -1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           boxes.shape)
    return {"Boxes": boxes, "Variances": var}


@register_op("anchor_generator", propagate_seqlen=False)
def _anchor_generator(ctx, Input):
    """RPN anchors in absolute pixels (reference anchor_generator_op.h).
    Outputs Anchors/Variances [H, W, num_anchors, 4]."""
    sizes = [float(s) for s in ctx.attr("anchor_sizes", [64.0, 128.0, 256.0])]
    ratios = [float(r) for r in ctx.attr("aspect_ratios", [0.5, 1.0, 2.0])]
    stride = [float(s) for s in ctx.attr("stride", [16.0, 16.0])]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    offset = ctx.attr("offset", 0.5)
    feat_h, feat_w = Input.shape[2], Input.shape[3]

    wh = []
    for r in ratios:
        for s in sizes:
            area = s * s
            w = math.sqrt(area / r)
            wh.append((w, w * r))
    wh = jnp.asarray(wh, jnp.float32)
    cx = (jnp.arange(feat_w) + offset) * stride[0]
    cy = (jnp.arange(feat_h) + offset) * stride[1]
    cx = jnp.broadcast_to(cx[None, :, None], (feat_h, feat_w, wh.shape[0]))
    cy = jnp.broadcast_to(cy[:, None, None], (feat_h, feat_w, wh.shape[0]))
    half_w, half_h = wh[None, None, :, 0] / 2, wh[None, None, :, 1] / 2
    anchors = jnp.stack([cx - half_w, cy - half_h, cx + half_w, cy + half_h],
                        -1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return {"Anchors": anchors, "Variances": var}


# ---------------------------------------------------------------------------
# IoU / coding / matching
# ---------------------------------------------------------------------------

def _iou_matrix(x, y, normalized=True):
    """[N,4] x [M,4] -> [N,M] (reference iou_similarity_op.h IOUSimilarity)."""
    off = 0.0 if normalized else 1.0
    area_x = (x[:, 2] - x[:, 0] + off) * (x[:, 3] - x[:, 1] + off)
    area_y = (y[:, 2] - y[:, 0] + off) * (y[:, 3] - y[:, 1] + off)
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", propagate_seqlen=False)
def _iou_similarity(ctx, X, Y):
    if X.ndim == 3:  # batched [B,N,4] vs [B,M,4] or shared [M,4]
        y = Y if Y.ndim == 3 else jnp.broadcast_to(Y, (X.shape[0],) + Y.shape)
        return {"Out": jax.vmap(_iou_matrix)(X, y)}
    return {"Out": _iou_matrix(X, Y)}


_ENC_EPS = 1e-9  # zero-size (padded) boxes must not produce -inf deltas


def _center_size(boxes, off):
    """ltrb [..., 4] -> (cx, cy, w, h)."""
    w = boxes[..., 2] - boxes[..., 0] + off
    h = boxes[..., 3] - boxes[..., 1] + off
    cx = (boxes[..., 2] + boxes[..., 0]) / 2
    cy = (boxes[..., 3] + boxes[..., 1]) / 2
    return cx, cy, w, h


def _encode_deltas(tcx, tcy, tw, th, pcx, pcy, pw, ph, v):
    """Shared center-size encode (reference box_coder_op.h EncodeCenterSize
    body); eps-guarded log so padded zero-size targets stay finite."""
    dx = (tcx - pcx) / pw / v[..., 0]
    dy = (tcy - pcy) / ph / v[..., 1]
    dw = jnp.log(jnp.maximum(jnp.abs(tw / pw), _ENC_EPS)) / v[..., 2]
    dh = jnp.log(jnp.maximum(jnp.abs(th / ph), _ENC_EPS)) / v[..., 3]
    return jnp.stack([dx, dy, dw, dh], -1)


@register_op("box_coder", propagate_seqlen=False)
def _box_coder(ctx, PriorBox, TargetBox, PriorBoxVar=None):
    """Center-size encode/decode (reference box_coder_op.h:40).
    encode: TargetBox [N,4] gt vs PriorBox [M,4] -> [N,M,4] deltas.
    decode: TargetBox [N,M,4] deltas -> [N,M,4] boxes."""
    code_type = ctx.attr("code_type", "encode_center_size")
    normalized = ctx.attr("box_normalized", True)
    off = 0.0 if normalized else 1.0
    pcx, pcy, pw, ph = _center_size(PriorBox, off)
    v = PriorBoxVar if PriorBoxVar is not None else jnp.ones_like(PriorBox)

    if code_type.startswith("encode"):
        tcx, tcy, tw, th = _center_size(TargetBox, off)
        return {"OutputBox": _encode_deltas(
            tcx[:, None], tcy[:, None], tw[:, None], th[:, None],
            pcx[None, :], pcy[None, :], pw[None, :], ph[None, :],
            v[None, :])}

    d = TargetBox                                       # [N, M, 4]
    cx = v[None, :, 0] * d[..., 0] * pw[None, :] + pcx[None, :]
    cy = v[None, :, 1] * d[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(v[None, :, 2] * d[..., 2]) * pw[None, :]
    h = jnp.exp(v[None, :, 3] * d[..., 3]) * ph[None, :]
    out = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - off, cy + h / 2 - off], -1)
    return {"OutputBox": out}


def _bipartite_match_one(dist, threshold, match_type):
    """dist [N, M] (rows=gt, cols=priors). Greedy global-max matching
    (reference bipartite_match_op.cc BipartiteMatch), then optional
    per_prediction filling of unmatched cols above `threshold`."""
    N, M = dist.shape
    init = (jnp.zeros((N,), bool),
            jnp.full((M,), -1, jnp.int32),
            jnp.zeros((M,), dist.dtype))

    def body(_, carry):
        row_used, col_to_row, col_dist = carry
        mask = (~row_used)[:, None] & (col_to_row < 0)[None, :]
        masked = jnp.where(mask, dist, -1.0)
        flat = jnp.argmax(masked)
        i, j = flat // M, flat % M
        best = masked.reshape(-1)[flat]
        take = best > 0
        row_used = row_used.at[i].set(jnp.where(take, True, row_used[i]))
        col_to_row = col_to_row.at[j].set(
            jnp.where(take, i.astype(jnp.int32), col_to_row[j]))
        col_dist = col_dist.at[j].set(jnp.where(take, best, col_dist[j]))
        return row_used, col_to_row, col_dist

    row_used, col_to_row, col_dist = lax.fori_loop(0, min(N, M), body, init)
    if match_type == "per_prediction":
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)   # [M]
        best_val = jnp.max(dist, axis=0)
        fill = (col_to_row < 0) & (best_val >= threshold)
        col_to_row = jnp.where(fill, best_row, col_to_row)
        col_dist = jnp.where(fill, best_val, col_dist)
    return col_to_row, col_dist


@register_op("bipartite_match", propagate_seqlen=False)
def _bipartite_match(ctx, DistMat):
    threshold = ctx.attr("dist_threshold", 0.5)
    match_type = ctx.attr("match_type", "bipartite")
    dist = DistMat if DistMat.ndim == 3 else DistMat[None]
    idx, d = jax.vmap(lambda m: _bipartite_match_one(m, threshold,
                                                     match_type))(dist)
    if DistMat.ndim == 2:
        idx, d = idx[0], d[0]
    return {"ColToRowMatchIndices": idx, "ColToRowMatchDist": d}


@register_op("target_assign", propagate_seqlen=False)
def _target_assign(ctx, X, MatchIndices, NegMask=None):
    """Gather per-prior targets by match index (reference
    target_assign_op.h): X [B, N, K] per-gt values, MatchIndices [B, M]
    (-1 = unmatched -> mismatch_value). NegMask [B, M] optionally forces
    entries to mismatch_value (the reference's NegIndices analog)."""
    mismatch = ctx.attr("mismatch_value", 0.0)
    idx = jnp.maximum(MatchIndices, 0)
    out = jnp.take_along_axis(X, idx[..., None], axis=1)
    matched = (MatchIndices >= 0)
    if NegMask is not None:
        matched = matched & (NegMask == 0)
    out = jnp.where(matched[..., None], out,
                    jnp.asarray(mismatch, out.dtype))
    wt = matched.astype(X.dtype)[..., None]
    return {"Out": out, "OutWeight": wt}


# ---------------------------------------------------------------------------
# NMS / mining / misc
# ---------------------------------------------------------------------------

def _nms_one_class(iou_full, scores, score_threshold, nms_threshold, eta,
                   top_k):
    """scores [M], shared iou_full [M,M] -> keep mask [M] (reference
    multiclass_nms_op.cc NMSFast: sort desc, suppress by IoU; the
    adaptive threshold decays by eta after each kept box when eta < 1,
    :NMSFast tail). The IoU matrix is computed ONCE per image and gathered
    per class's sort order — classes share the same boxes."""
    M = scores.shape[0]
    k = min(top_k, M) if top_k > 0 else M
    order = jnp.argsort(-scores)
    ss = scores[order]
    iou = iou_full[order][:, order]
    valid = ss > score_threshold

    def body(i, carry):
        keep, th = carry
        sup = jnp.any(keep & (iou[i] > th) & (jnp.arange(M) < i))
        ki = valid[i] & ~sup & (i < k)
        th = jnp.where(ki & (eta < 1.0) & (th > 0.5), th * eta, th)
        return keep.at[i].set(ki), th

    keep_sorted, _ = lax.fori_loop(
        0, M, body, (jnp.zeros((M,), bool),
                     jnp.asarray(nms_threshold, jnp.float32)))
    return jnp.zeros((M,), bool).at[order].set(keep_sorted)


@register_op("multiclass_nms", propagate_seqlen=False)
def _multiclass_nms(ctx, BBoxes, Scores):
    """BBoxes [B,M,4], Scores [B,C,M] -> Out [B, keep_top_k, 6]
    (label, score, ltrb) padded with label=-1, plus Count [B]
    (reference multiclass_nms_op.cc emits a LoD tensor; the static padded
    layout is the TPU redesign — see module docstring)."""
    score_threshold = ctx.attr("score_threshold", 0.01)
    nms_top_k = int(ctx.attr("nms_top_k", 400))
    keep_top_k = int(ctx.attr("keep_top_k", 200))
    nms_threshold = ctx.attr("nms_threshold", 0.3)
    eta = ctx.attr("nms_eta", 1.0)
    background = int(ctx.attr("background_label", 0))
    normalized = ctx.attr("normalized", True)
    B, C, M = Scores.shape
    if keep_top_k <= 0:
        keep_top_k = C * M

    def per_image(boxes, scores):
        iou_full = _iou_matrix(boxes, boxes, normalized=normalized)
        cand_scores, cand_labels, cand_boxes = [], [], []
        for c in range(C):
            if c == background:
                continue
            keep = _nms_one_class(iou_full, scores[c], score_threshold,
                                  nms_threshold, eta, nms_top_k)
            cand_scores.append(jnp.where(keep, scores[c], -1.0))
            cand_labels.append(jnp.full((M,), c, jnp.float32))
            cand_boxes.append(boxes)
        s = jnp.concatenate(cand_scores)
        l = jnp.concatenate(cand_labels)
        bx = jnp.concatenate(cand_boxes, axis=0)
        k = min(keep_top_k, s.shape[0])
        top_s, top_i = lax.top_k(s, k)
        top_l = jnp.where(top_s > -1.0, l[top_i], -1.0)
        top_b = bx[top_i]
        out = jnp.concatenate([top_l[:, None], top_s[:, None], top_b], -1)
        if k < keep_top_k:
            pad = jnp.full((keep_top_k - k, 6), -1.0, out.dtype)
            out = jnp.concatenate([out, pad], 0)
        count = jnp.sum(top_s > -1.0).astype(jnp.int32)
        return out, count

    outs, counts = jax.vmap(per_image)(BBoxes, Scores)
    return {"Out": outs, "Count": counts}


@register_op("mine_hard_examples", propagate_seqlen=False)
def _mine_hard_examples(ctx, ClsLoss, MatchIndices, LocLoss=None,
                        MatchDist=None):
    """Hard-negative mining (reference mine_hard_examples_op.cc,
    max_negative mode): among unmatched priors whose best-match overlap is
    BELOW neg_dist_threshold (near-positives are excluded from mining, as
    in the reference), pick the neg_pos_ratio * num_pos highest-loss ones
    per image. Returns NegMask [B, M] (the reference's variable-length
    NegIndices as a static mask) and UpdatedMatchIndices."""
    neg_pos_ratio = ctx.attr("neg_pos_ratio", 3.0)
    neg_overlap = ctx.attr("neg_dist_threshold", 0.5)
    loss = ClsLoss if LocLoss is None else ClsLoss + LocLoss
    B, M = MatchIndices.shape
    if MatchDist is None:
        MatchDist = jnp.zeros((B, M), loss.dtype)

    def per_image(l, match, dist):
        pos = match >= 0
        candidate = (~pos) & (dist < neg_overlap)
        num_pos = jnp.sum(pos)
        num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32),
                              jnp.sum(candidate))
        neg_loss = jnp.where(candidate, l, -jnp.inf)
        order = jnp.argsort(-neg_loss)
        rank = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M))
        neg_mask = candidate & (rank < num_neg)
        return neg_mask.astype(jnp.int32)

    neg = jax.vmap(per_image)(loss, MatchIndices, MatchDist)
    return {"NegMask": neg, "UpdatedMatchIndices": MatchIndices}


@register_op("polygon_box_transform", propagate_seqlen=False)
def _polygon_box_transform(ctx, Input):
    """reference polygon_box_transform_op.cc:44-46 (and the .cu kernel
    :35-37): even channels get id_w - in, odd channels id_h - in — quad
    geometry offsets -> absolute pixel coordinates. Note: there is NO 4x
    grid scaling in the reference kernels; EAST-style 1/4-resolution
    rescaling happens in user postprocessing, not in this op."""
    B, C, H, W = Input.shape
    xg = jnp.broadcast_to(jnp.arange(W, dtype=Input.dtype)[None, :], (H, W))
    yg = jnp.broadcast_to(jnp.arange(H, dtype=Input.dtype)[:, None], (H, W))
    grid = jnp.stack([xg, yg] * (C // 2), 0)            # [C, H, W]
    return {"Output": grid[None] - Input}


# ---------------------------------------------------------------------------
# ssd_loss building blocks (the reference computes these inside the python
# ssd_loss composition with reshape gymnastics; dedicated rules keep the
# per-prior pairing explicit and fusible)
# ---------------------------------------------------------------------------

@register_op("box_encode_per_prior", propagate_seqlen=False)
def _box_encode_per_prior(ctx, TargetBox, PriorBox, PriorBoxVar=None):
    """Per-prior center-size encoding: TargetBox [B, M, 4] already gathered
    onto priors, PriorBox [M, 4] -> deltas [B, M, 4] (same math as
    box_coder's encode, paired instead of cross-product)."""
    off = 0.0 if ctx.attr("box_normalized", True) else 1.0
    pcx, pcy, pw, ph = _center_size(PriorBox, off)
    v = PriorBoxVar if PriorBoxVar is not None else jnp.ones_like(PriorBox)
    tcx, tcy, tw, th = _center_size(TargetBox, off)
    return {"OutputBox": _encode_deltas(tcx, tcy, tw, th, pcx[None],
                                        pcy[None], pw[None], ph[None],
                                        v[None])}


@register_op("greater_equal_scalar0", propagate_seqlen=False)
def _greater_equal_scalar0(ctx, X):
    return {"Out": (X >= 0).astype(jnp.float32)}


@register_op("smooth_l1_elementwise", propagate_seqlen=False)
def _smooth_l1_elementwise(ctx, X):
    """Elementwise huber on |diff| (reference smooth_l1 kernel body)."""
    sigma2 = ctx.attr("sigma", 1.0) ** 2
    a = jnp.abs(X)
    return {"Out": jnp.where(a < 1.0 / sigma2, 0.5 * sigma2 * a * a,
                             a - 0.5 / sigma2)}


@register_op("softmax_ce_no_reduce", propagate_seqlen=False)
def _softmax_ce_no_reduce(ctx, Logits, Label):
    """Per-position CE: Logits [B, M, C], Label [B, M, 1] -> [B, M]."""
    logp = jax.nn.log_softmax(Logits.astype(jnp.float32), axis=-1)
    ids = Label.reshape(Label.shape[0], Label.shape[1]).astype(jnp.int32)
    ce = -jnp.take_along_axis(logp, ids[..., None], axis=-1)[..., 0]
    return {"Out": ce.astype(Logits.dtype)}


@register_op("rpn_target_assign", propagate_seqlen=False)
def _rpn_target_assign(ctx, Anchor, GtBox, DistMat):
    """RPN anchor labeling (reference rpn_target_assign_op.cc). The
    reference randomly subsamples positives/negatives; random subsampling
    on TPU would burn a PRNG per step for no modelling benefit, so the
    highest-IoU positives / lowest-IoU negatives are kept deterministically
    (documented redesign). Outputs Labels [B, M] (1 pos, 0 neg, -1 ignore)
    and per-anchor MatchIndices."""
    pos_th = ctx.attr("rpn_positive_overlap", 0.7)
    neg_th = ctx.attr("rpn_negative_overlap", 0.3)
    batch_size = int(ctx.attr("rpn_batch_size_per_im", 256))
    fg_frac = ctx.attr("rpn_fg_fraction", 0.5)
    dist = DistMat if DistMat.ndim == 3 else DistMat[None]
    B, N, M = dist.shape
    num_fg = int(batch_size * fg_frac)

    def per_image(d):
        best_gt = jnp.argmax(d, axis=0).astype(jnp.int32)    # [M]
        best_iou = jnp.max(d, axis=0)
        # anchors with max IoU for some gt are positive too
        best_anchor = jnp.argmax(d, axis=1)                  # [N]
        forced = jnp.zeros((M,), bool).at[best_anchor].set(True)
        pos = (best_iou >= pos_th) | forced
        neg = (best_iou < neg_th) & ~pos
        # deterministic subsample: top IoU positives, bottom IoU negatives
        pos_rank = jnp.zeros((M,), jnp.int32).at[
            jnp.argsort(-jnp.where(pos, best_iou, -jnp.inf))].set(
            jnp.arange(M))
        pos = pos & (pos_rank < num_fg)
        n_neg = batch_size - jnp.minimum(jnp.sum(pos), num_fg)
        neg_rank = jnp.zeros((M,), jnp.int32).at[
            jnp.argsort(jnp.where(neg, best_iou, jnp.inf))].set(
            jnp.arange(M))
        neg = neg & (neg_rank < n_neg)
        labels = jnp.where(pos, 1, jnp.where(neg, 0, -1)).astype(jnp.int32)
        match = jnp.where(pos, best_gt, -1)
        return labels, match

    labels, match = jax.vmap(per_image)(dist)
    if DistMat.ndim == 2:
        labels, match = labels[0], match[0]
    return {"Labels": labels, "MatchIndices": match}


@register_op("detection_map", propagate_seqlen=False)
def _detection_map(ctx, DetectRes, Label):
    """Batch mean-average-precision (reference detection_map_op.h).

    Static-shape redesign of the LoD inputs: DetectRes [B,D,6] rows
    (label, score, x1,y1,x2,y2) padded with label=-1 (the multiclass_nms
    output layout); Label [B,G,6] rows (label, difficult, x1,y1,x2,y2)
    padded with label=-1. Greedy VOC matching runs as a lax.scan over the
    globally score-sorted detections carrying the per-GT matched mask, so
    two detections can never claim the same ground-truth box.
    """
    class_num = int(ctx.attr("class_num"))
    background = int(ctx.attr("background_label", 0))
    thr = float(ctx.attr("overlap_threshold", 0.5))
    eval_difficult = bool(ctx.attr("evaluate_difficult", True))
    ap_version = ctx.attr("ap_version", "integral")

    B, D, _ = DetectRes.shape
    G = Label.shape[1]
    det_label = DetectRes[:, :, 0].reshape(-1)              # [N]
    det_score = DetectRes[:, :, 1].reshape(-1)
    det_box = DetectRes[:, :, 2:6].reshape(-1, 4)
    img_idx = jnp.repeat(jnp.arange(B), D)

    gt_label = Label[:, :, 0]                               # [B,G]
    gt_difficult = Label[:, :, 1] > 0.5
    gt_box = Label[:, :, 2:6]                               # [B,G,4]
    gt_valid = gt_label >= 0

    valid = det_label >= 0
    order = jnp.argsort(jnp.where(valid, -det_score, jnp.inf))
    det_label = det_label[order]
    det_box = det_box[order]
    img_idx = img_idx[order]
    valid = valid[order]

    def iou(box, boxes):
        ix1 = jnp.maximum(box[0], boxes[:, 0])
        iy1 = jnp.maximum(box[1], boxes[:, 1])
        ix2 = jnp.minimum(box[2], boxes[:, 2])
        iy2 = jnp.minimum(box[3], boxes[:, 3])
        iw = jnp.maximum(ix2 - ix1, 0.0)
        ih = jnp.maximum(iy2 - iy1, 0.0)
        inter = iw * ih
        a1 = jnp.maximum(box[2] - box[0], 0.0) * jnp.maximum(box[3] - box[1], 0.0)
        a2 = (jnp.maximum(boxes[:, 2] - boxes[:, 0], 0.0)
              * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0.0))
        return inter / jnp.maximum(a1 + a2 - inter, 1e-10)

    def step(matched, det):
        lbl, box, img, ok = det
        g_lbl = gt_label[img]                                # [G]
        g_box = gt_box[img]
        same = (g_lbl == lbl) & gt_valid[img]
        ious = jnp.where(same, iou(box, g_box), -1.0)
        best = jnp.argmax(ious)
        hit = ious[best] >= thr
        diff = gt_difficult[img, best]
        already = matched[img, best]
        ignore = hit & diff & (not eval_difficult)
        tp = ok & hit & ~already & ~(diff & (not eval_difficult))
        fp = ok & ~ignore & ~tp
        matched = matched.at[img, best].set(already | tp)
        return matched, (tp, fp)

    matched0 = jnp.zeros((B, G), bool)
    _, (tp, fp) = jax.lax.scan(
        step, matched0, (det_label, det_box, img_idx, valid))

    classes = jnp.arange(class_num)                          # [C]
    countable = gt_valid & (eval_difficult | ~gt_difficult)
    npos = jnp.sum((gt_label[None, :, :] == classes[:, None, None])
                   & countable[None, :, :], axis=(1, 2)).astype(jnp.float32)

    cls_mask = (det_label[None, :] == classes[:, None])      # [C,N]
    tp_c = jnp.cumsum(tp[None, :] * cls_mask, axis=1).astype(jnp.float32)
    fp_c = jnp.cumsum(fp[None, :] * cls_mask, axis=1).astype(jnp.float32)
    prec = tp_c / jnp.maximum(tp_c + fp_c, 1e-10)
    n_safe = jnp.maximum(npos, 1.0)[:, None]
    if ap_version == "11point":
        recall = tp_c / n_safe
        ts = jnp.arange(11, dtype=jnp.float32) / 10.0        # [11]
        at_t = jnp.max(jnp.where((recall[:, None, :] >= ts[None, :, None])
                                 & cls_mask[:, None, :], prec[:, None, :],
                                 0.0), axis=2)               # [C,11]
        ap = jnp.mean(at_t, axis=1)
    else:
        # integral: each TP adds precision-at-that-point / npos
        ap = jnp.sum(prec * (tp[None, :] * cls_mask), axis=1) / n_safe[:, 0]

    has_pos = (npos > 0) & (classes != background)
    m = jnp.sum(jnp.where(has_pos, ap, 0.0)) / jnp.maximum(
        jnp.sum(has_pos.astype(jnp.float32)), 1.0)
    return {"MAP": m.reshape((1,))}
