"""Sequence op lowerings over padded variable-length batches.

Capability parity with the reference's LoD sequence op family (reference:
paddle/fluid/operators/sequence_{pool,softmax,expand,...}_op.cc; LoD design
doc/fluid/design/concepts/lod_tensor.md). TPU-native redesign: LoD offset
tables become a `@SEQLEN` length vector over a padded dense batch; every op
here is masking + reductions that XLA fuses, preserving the reference's
"no effective padding compute" property for the common ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _time_mask(SeqLen, T, dtype=jnp.float32):
    return (jnp.arange(T)[None, :] < SeqLen.reshape(-1, 1)).astype(dtype)




def _flat_rows(a):
    """[B, S, rest...] -> [(B*S), rest...] — the innermost-level adapter:
    nested (level-2) inputs run the level-1 rule on flattened (doc,
    sentence) rows (reference lod_tensor.h:110 — sequence ops act on the
    innermost LoD level)."""
    return a.reshape((a.shape[0] * a.shape[1],) + tuple(a.shape[2:]))


def _unflat_rows(a, B, S):
    return a.reshape((B, S) + tuple(a.shape[1:]))


@register_op("sequence_pool", propagate_seqlen=False)
def _sequence_pool(ctx, X, SeqLen=None):
    """[B, T, D] (+lengths) -> [B, D]. pool_type in
    {average, sum, sqrt, max, last, first} (reference sequence_pool_op.cc).

    Nested LoD: with X = [B, S, T, D] and SeqLen = inner lengths [B, S],
    pooling collapses the INNERMOST level (reference semantics: sequence
    ops act on the last LoD level) -> [B, S, D]; the outer level rides on
    via the layer's companion aliasing."""
    ptype = ctx.attr("pooltype", "AVERAGE").lower()
    if SeqLen is not None and SeqLen.ndim == 2:
        B, S, T = X.shape[0], X.shape[1], X.shape[2]
        x2 = X.reshape((B * S, T) + tuple(X.shape[3:]))
        out = _sequence_pool(ctx, x2, SeqLen.reshape(-1))["Out"]
        return {"Out": out.reshape((B, S) + tuple(out.shape[1:]))}
    B, T = X.shape[0], X.shape[1]
    L = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    m = _time_mask(L, T, X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    if ptype == "sum":
        out = jnp.sum(X * m, axis=1)
    elif ptype == "average":
        out = jnp.sum(X * m, axis=1) / jnp.maximum(L.astype(X.dtype), 1.0).reshape(-1, *([1] * (X.ndim - 2)))
    elif ptype == "sqrt":
        out = jnp.sum(X * m, axis=1) / jnp.sqrt(jnp.maximum(L.astype(X.dtype), 1.0)).reshape(-1, *([1] * (X.ndim - 2)))
    elif ptype == "max":
        neg = jnp.finfo(X.dtype).min if jnp.issubdtype(X.dtype, jnp.floating) else jnp.iinfo(X.dtype).min
        out = jnp.max(jnp.where(m > 0, X, neg), axis=1)
    elif ptype == "last":
        idx = jnp.maximum(L - 1, 0).reshape(-1, 1, *([1] * (X.ndim - 2)))
        out = jnp.take_along_axis(X, idx.astype(jnp.int32), axis=1)[:, 0]
    elif ptype == "first":
        out = X[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": out}


@register_op("sequence_softmax", propagate_seqlen=False)
def _sequence_softmax(ctx, X, SeqLen=None):
    """Softmax over the time axis within each row's valid prefix."""
    if SeqLen is not None and SeqLen.ndim == 2:           # nested LoD
        B, S = X.shape[0], X.shape[1]
        out = _sequence_softmax(ctx, _flat_rows(X), SeqLen.reshape(-1))
        return {"Out": _unflat_rows(out["Out"], B, S)}
    B, T = X.shape[0], X.shape[1]
    L = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    m = _time_mask(L, T, jnp.float32)
    while m.ndim < X.ndim:
        m = m[..., None]
    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(m > 0, X.astype(jnp.float32), neg)
    out = jax.nn.softmax(logits, axis=1) * m
    return {"Out": out.astype(X.dtype)}


@register_op("sequence_expand", propagate_seqlen=False)
def _sequence_expand(ctx, X, Y, SeqLen=None):
    """Broadcast per-row features over Y's time axis
    (reference sequence_expand_op.cc, ref_level=0 case):
    X [B, D] or [B, 1, D] -> [B, T_y, D]."""
    if Y.ndim == 4:                                      # nested LoD Y
        # X per (doc, sentence) row: [B, S, D] -> [B, S, T_y, D]
        x = X if X.ndim == 4 else X[:, :, None, :]
        T = Y.shape[2]
        return {"Out": jnp.broadcast_to(
            x, (x.shape[0], x.shape[1], T, x.shape[-1]))}
    x = X if X.ndim == 3 else X[:, None, :]
    T = Y.shape[1]
    return {"Out": jnp.broadcast_to(x, (x.shape[0], T, x.shape[-1]))}


@register_op("sequence_reshape", propagate_seqlen=False)
def _sequence_reshape(ctx, X, SeqLen=None):
    """Repack [B,T,D] -> [B, T*D/new_dim, new_dim]; row lengths scale by
    D/new_dim (reference sequence_reshape_op.cc recomputes the LoD the same
    way and requires len*D % new_dim == 0)."""
    new_dim = ctx.attr("new_dim")
    if SeqLen is not None and SeqLen.ndim == 2:           # nested LoD
        B, S = X.shape[0], X.shape[1]
        sub = _sequence_reshape(ctx, _flat_rows(X), SeqLen.reshape(-1))
        return {"Out": _unflat_rows(sub["Out"], B, S),
                "OutLen": sub["OutLen"].reshape(B, S)}
    B, T, D = X.shape
    assert (T * D) % new_dim == 0
    outs = {"Out": X.reshape(B, (T * D) // new_dim, new_dim)}
    if SeqLen is not None:
        outs["OutLen"] = (SeqLen * D) // new_dim
    return outs


@register_op("sequence_concat", propagate_seqlen=False)
def _sequence_concat(ctx, X, SeqLen=None):
    """Per-sequence concatenation (reference sequence_concat_op.cc): row b
    of the output is concat_i(x_i[b, :len_i[b]]), left-aligned in the
    padded layout, OutLen = sum_i len_i. The old rule concatenated the
    padded time axes, embedding padding mid-sequence for any ragged row.

    Static-shape realization: concatenate the padded inputs (static
    offsets P_i), then gather each output position from segment i at
    P_i + (t - start_i[b]) where start_i[b] = cumsum of valid lengths.

    Nested (level-2) inputs run the same rule on flattened (doc,
    sentence) rows — innermost-level semantics, reference
    lod_tensor.h:110."""
    xs = X if isinstance(X, list) else [X]
    lens = SeqLen if isinstance(SeqLen, list) else \
        [SeqLen] * (1 if SeqLen is not None else 0)
    if len(lens) < len(xs):
        lens = lens + [None] * (len(xs) - len(lens))
    nested = any(l is not None and l.ndim == 2 for l in lens)
    if nested:
        B, S = xs[0].shape[0], xs[0].shape[1]
        sub = _sequence_concat(
            ctx, [_flat_rows(x) for x in xs],
            [None if l is None else l.reshape(-1) for l in lens])
        return {"Out": _unflat_rows(sub["Out"], B, S),
                "OutLen": sub["OutLen"].reshape(B, S)}
    B = xs[0].shape[0]
    Ts = [int(x.shape[1]) for x in xs]
    if all(l is None for l in lens):
        # no lengths anywhere: every row is full, padded concat IS the answer
        return {"Out": jnp.concatenate(xs, axis=1),
                "OutLen": jnp.full((B,), sum(Ts), jnp.int32)}
    L = jnp.stack([jnp.full((B,), t, jnp.int32) if l is None
                   else l.reshape(B).astype(jnp.int32)
                   for l, t in zip(lens, Ts)], axis=1)        # [B, N]
    starts = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), jnp.cumsum(L, axis=1)], axis=1)
    xcat = jnp.concatenate(xs, axis=1)                        # [B, sum(Ts), ...]
    P = [0]
    for t_i in Ts:
        P.append(P[-1] + t_i)                                 # static offsets
    T_out = P[-1]
    t = jnp.arange(T_out, dtype=jnp.int32)[None, :]           # [1, T_out]
    src = jnp.zeros((B, T_out), jnp.int32)
    for i in range(len(xs)):
        in_seg = (t >= starts[:, i:i + 1]) & (t < starts[:, i + 1:i + 2])
        src = jnp.where(in_seg, int(P[i]) + t - starts[:, i:i + 1], src)
    gidx = src.reshape((B, T_out) + (1,) * (xcat.ndim - 2))
    out = jnp.take_along_axis(
        xcat, jnp.broadcast_to(gidx, (B, T_out) + xcat.shape[2:]), axis=1)
    total = starts[:, -1]
    mask = (t < total[:, None]).reshape((B, T_out) + (1,) * (xcat.ndim - 2))
    out = jnp.where(mask, out, jnp.zeros((), out.dtype))
    return {"Out": out, "OutLen": total}


@register_op("sequence_slice", propagate_seqlen=False)
def _sequence_slice(ctx, X, Offset, Length):
    """Per-sequence sub-slices (reference sequence_slice_op.cc): row b of
    the output is X[b, off_b : off_b + len_b], left-aligned in the padded
    [B, T, ...] layout with OutLen = len_b. Dynamic STARTS are fine under
    XLA (a gather); only dynamic shapes are not — the old raise conflated
    the two."""
    if ctx.attr("nested", False):
        # nested LoD (explicit attr from the layer — a shape heuristic
        # would misread level-1 [B, 1, D] inputs): slice each
        # (doc, sentence) row independently
        B, S = X.shape[0], X.shape[1]
        sub = _slice_rows(_flat_rows(X), Offset.reshape(-1),
                          Length.reshape(-1))
        return {"Out": _unflat_rows(sub["Out"], B, S),
                "OutLen": sub["OutLen"].reshape(B, S)}
    return _slice_rows(X, Offset, Length)


def _slice_rows(X, Offset, Length):
    B, T = X.shape[0], X.shape[1]
    # offsets and lengths clamp to the tensor bound: a compiled XLA
    # program cannot raise on runtime values (the reference kernel
    # host-asserts offset+length <= seqlen), and clamping beats the
    # silent row duplication an unclamped gather would produce. Offset
    # is clamped first so a negative offset degrades to an offset-0
    # slice instead of an over-long one built from duplicated rows.
    off = jnp.clip(Offset.reshape(B).astype(jnp.int32), 0, T)
    ln = jnp.clip(Length.reshape(B).astype(jnp.int32), 0, T - off)
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    idx = jnp.clip(off[:, None] + t, 0, T - 1)          # [B, T]
    gidx = idx.reshape((B, T) + (1,) * (X.ndim - 2))
    out = jnp.take_along_axis(
        X, jnp.broadcast_to(gidx, (B, T) + X.shape[2:]), axis=1)
    mask = (t < ln[:, None]).reshape((B, T) + (1,) * (X.ndim - 2))
    out = jnp.where(mask, out, jnp.zeros((), out.dtype))
    return {"Out": out, "OutLen": ln}


@register_op("sequence_conv", propagate_seqlen=False)
def _sequence_conv(ctx, X, Filter, SeqLen=None, PaddingData=None):
    """Context-window conv over time (reference sequence_conv_op.cc):
    X [B, T, D], Filter [ctx_len*D, M] -> [B, T, M]."""
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", -(ctx_len // 2))
    if SeqLen is not None and SeqLen.ndim == 2:           # nested LoD
        B, S = X.shape[0], X.shape[1]
        sub = _sequence_conv(ctx, _flat_rows(X), Filter,
                             SeqLen.reshape(-1), PaddingData)
        return {"Out": _unflat_rows(sub["Out"], B, S)}
    B, T, D = X.shape
    L = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    m = _time_mask(L, T, X.dtype)[..., None]
    xm = X * m
    cols = []
    for i in range(ctx_len):
        shift = ctx_start + i
        rolled = jnp.roll(xm, -shift, axis=1)
        t = jnp.arange(T)
        valid = ((t + shift >= 0) & (t + shift < T)).astype(X.dtype).reshape(1, T, 1)
        cols.append(rolled * valid)
    ctx_mat = jnp.concatenate(cols, axis=-1)          # [B, T, ctx_len*D]
    out = ctx_mat @ Filter                            # [B, T, M]
    return {"Out": out * m}


@register_op("sequence_erase", propagate_seqlen=False)
def _sequence_erase(ctx, X, SeqLen=None):
    """Remove the attr `tokens` from each sequence and compact left
    (reference sequence_erase_op.cc). Static-shape stream compaction: a
    STABLE argsort of the drop mask moves kept entries to the front in
    order; OutLen carries the shrunken lengths. The output stays padded
    [B, T] — the 'dynamic length' the old raise pointed at lives in the
    lengths companion, exactly like every other sequence op here."""
    tokens = [int(v) for v in (ctx.attr("tokens", []) or [])]
    if SeqLen is not None and SeqLen.ndim == 2:           # nested LoD
        B, S = X.shape[0], X.shape[1]
        sub = _sequence_erase(ctx, _flat_rows(X), SeqLen.reshape(-1))
        return {"Out": _unflat_rows(sub["Out"], B, S),
                "OutLen": sub["OutLen"].reshape(B, S)}
    squeeze = X.ndim == 3 and X.shape[-1] == 1   # Paddle ids are often [B,T,1]
    ids = X.reshape(X.shape[0], X.shape[1]) if squeeze else X
    B, T = ids.shape
    L = (SeqLen.reshape(-1) if SeqLen is not None
         else jnp.full((B,), T, jnp.int32))      # tolerate [B] or [B,1]
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    keep = t < L[:, None]
    for tok in tokens:
        keep = keep & (ids != tok)
    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
    order = jnp.argsort(~keep, axis=1, stable=True)     # kept first, in order
    compacted = jnp.take_along_axis(ids, order, axis=1)
    out = jnp.where(t < new_len[:, None], compacted, jnp.zeros((), ids.dtype))
    if squeeze:
        out = out[..., None]
    return {"Out": out, "OutLen": new_len}


@register_op("sequence_expand_as", propagate_seqlen=False)
def _sequence_expand_as(ctx, X, Y):
    x = X if X.ndim == 3 else X[:, None, :]
    return {"Out": jnp.broadcast_to(x, (x.shape[0], Y.shape[1], x.shape[-1]))}


@register_op("row_conv", propagate_seqlen=False)
def _row_conv(ctx, X, Filter, SeqLen=None):
    """Lookahead row convolution (reference row_conv_op.cc):
    X [B, T, D], Filter [future_ctx, D]."""
    future, D = Filter.shape
    B, T, _ = X.shape
    out = jnp.zeros_like(X)
    for i in range(future):
        rolled = jnp.roll(X, -i, axis=1)
        t = jnp.arange(T)
        valid = (t + i < T).astype(X.dtype).reshape(1, T, 1)
        out = out + rolled * valid * Filter[i].reshape(1, 1, D)
    if SeqLen is not None:
        out = out * _time_mask(SeqLen, T, X.dtype)[..., None]
    return {"Out": out}
