"""Tensor-array + LoD-rank-table op lowerings (dynamic-RNN plumbing).

Capability parity with the reference's LoDTensorArray machinery (reference:
paddle/fluid/operators/tensor_array_read_write_op.cc,
lod_rank_table_op.cc, lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc,
shrink_rnn_memory_op.cc, max_sequence_len_op.cc,
framework/lod_tensor_array.h, framework/lod_rank_table.h).

TPU-native redesign: the reference's LoDTensorArray is a host-side
vector<LoDTensor> that grows per `while` iteration — impossible under XLA's
static shapes. Here a tensor array is a pre-allocated dense buffer
`[capacity, ...]` living in the traced program, written/read with
`lax.dynamic_update_index_in_dim` / `dynamic_index_in_dim`, so the whole
while/scan loop stays on-device. The companion scalar `name@ALEN` (int32)
tracks the logical length, mirroring `@SEQLEN` for sequences.

The LoD rank table (sort-sequences-by-length so the batch can shrink as
short rows finish — shrink_rnn_memory) is replaced by masking on the padded
representation: the "rank table" value is simply the row-lengths vector, and
`shrink_memory` becomes a per-row `where(t < len, new, old)` select. Same
numerics, no data-dependent shapes, and XLA fuses the masks for free.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

# Default buffer capacity for arrays written before their extent is known
# (e.g. decode loops). lod_tensor_to_array sizes buffers exactly from T.
DEFAULT_ARRAY_CAPACITY = 128


def _as_index(i):
    return jnp.asarray(i).reshape(()).astype(jnp.int32)


@register_op("array_write", propagate_seqlen=False)
def _array_write(ctx, X, I, Array=None, ALen=None):
    """Write X at index I. Array is the pre-allocated [cap, ...] buffer; when
    absent (first write) a zero buffer of `capacity` entries is allocated at
    trace time (reference tensor_array_read_write_op.cc grows a vector).

    Overflow contract: lax.dynamic_update clamps out-of-range indices, which
    would silently corrupt slot cap-1; instead a write at I >= capacity is a
    NO-OP on the buffer while OutLen still records max(len, I+1) — so
    `array_length(arr) > capacity` is the runtime-checkable overflow signal
    (XLA programs cannot raise; reference host vectors grew unboundedly)."""
    i = _as_index(I)
    if Array is None:
        cap = int(ctx.attr("capacity", DEFAULT_ARRAY_CAPACITY))
        Array = jnp.zeros((cap,) + tuple(X.shape), X.dtype)
    if ALen is None:
        ALen = jnp.int32(0)
    in_range = i < Array.shape[0]
    buf = lax.dynamic_update_index_in_dim(Array, X.astype(Array.dtype),
                                          jnp.minimum(i, Array.shape[0] - 1), 0)
    buf = jnp.where(in_range, buf, Array)
    return {"Out": buf, "OutLen": jnp.maximum(ALen, i + 1)}


@register_op("array_read", propagate_seqlen=False)
def _array_read(ctx, Array, I):
    return {"Out": lax.dynamic_index_in_dim(Array, _as_index(I), 0,
                                            keepdims=False)}


@register_op("array_length", propagate_seqlen=False)
def _array_length(ctx, ALen):
    return {"Out": ALen.reshape(())}


@register_op("lod_rank_table", propagate_seqlen=False)
def _lod_rank_table(ctx, X, SeqLen=None):
    """The rank table degenerates to the lengths vector [B] (see module doc).
    With no @SEQLEN companion every row has the full time extent."""
    if SeqLen is not None:
        return {"Out": SeqLen.astype(jnp.int32)}
    B = X.shape[0]
    T = X.shape[1] if X.ndim > 1 else 1
    return {"Out": jnp.full((B,), T, jnp.int32)}


@register_op("max_sequence_len", propagate_seqlen=False)
def _max_sequence_len(ctx, RankTable):
    return {"Out": jnp.max(RankTable)}


@register_op("lod_tensor_to_array", propagate_seqlen=False)
def _lod_tensor_to_array(ctx, X, RankTable=None):
    """[B, T, ...] -> time-major buffer [T, B, ...] (the array has exactly T
    entries; entry t is the batch slice at step t). Reference
    lod_tensor_to_array_op.cc buckets rows by length; masking makes that
    unnecessary here."""
    buf = jnp.swapaxes(X, 0, 1)
    T = X.shape[1]
    return {"Out": buf, "OutLen": jnp.int32(T)}


@register_op("array_to_lod_tensor", propagate_seqlen=False)
def _array_to_lod_tensor(ctx, X, RankTable=None):
    """Inverse of lod_tensor_to_array: [T, B, ...] buffer -> [B, T, ...],
    re-attaching lengths (@SEQLEN) from the rank table."""
    out = jnp.swapaxes(X, 0, 1)
    outs = {"Out": out}
    if RankTable is not None:
        T = out.shape[1]
        mask = (jnp.arange(T)[None, :] < RankTable.reshape(-1, 1))
        m = mask.astype(out.dtype)
        while m.ndim < out.ndim:
            m = m[..., None]
        outs["Out"] = out * m
    return outs


@register_op("shrink_memory", propagate_seqlen=False)
def _shrink_memory(ctx, X, I, RankTable):
    """Reference shrink_rnn_memory_op.cc drops the rows whose sequence has
    ended at step I (batch physically shrinks). Padded analog: rows with
    len <= I are frozen by the caller's masked update; this op returns X with
    finished rows' contribution masked so downstream reductions ignore them."""
    i = _as_index(I)
    active = (RankTable.reshape(-1) > i)
    m = active.astype(X.dtype)
    while m.ndim < X.ndim:
        m = m[..., None]
    return {"Out": X * m}


@register_op("reorder_lod_tensor_by_rank", propagate_seqlen=False)
def _reorder_lod_tensor_by_rank(ctx, X, RankTable):
    """Reference reorder_lod_tensor_by_rank_op.cc sorts rows to rank-table
    order (longest first). Masking removes the need to sort, but the op is
    provided for program parity: rows are permuted by descending length."""
    order = jnp.argsort(-RankTable.reshape(-1), stable=True)
    return {"Out": jnp.take(X, order, axis=0), "OutIndex": order.astype(jnp.int32)}
