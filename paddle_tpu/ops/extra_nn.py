"""Breadth ops completing the reference nn.py layer surface: 3-D conv/pool,
image resize, crop, multiplex, roi_pool, label_smooth, metric ops.

Capability parity references: conv3d_op.cc, conv3d_transpose (conv_transpose
_op.cc), pool3d (pool_op.cc), bilinear_interp_op.cc, crop_op.cc,
random_crop_op.cc, multiplex_op.cc, roi_pool_op.cc, label_smooth_op.cc,
rank_loss_op.cc, mean_iou_op.cc, ctc_align_op.cc (greedy decode),
chunk_eval_op.cc, lod_reset_op.cc.

TPU-native: everything is expressed in lax/jnp so XLA maps the convs onto
the MXU and fuses the rest; roi_pool vmaps a gather-based pooling over the
ROI list instead of the reference's per-ROI CUDA kernel loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register_op("conv3d", propagate_seqlen=False)
def _conv3d(ctx, Input, Filter, Bias=None):
    """NCDHW conv (reference conv3d registration in conv_op.cc)."""
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    out = lax.conv_general_dilated(
        Input, Filter, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=ctx.attr("groups", 1) or 1,
    )
    if Bias is not None:
        out = out + Bias.reshape((1, -1, 1, 1, 1))
    return {"Output": out}


@register_op("conv3d_transpose", propagate_seqlen=False)
def _conv3d_transpose(ctx, Input, Filter, Bias=None):
    """Gradient-of-conv3d as a forward op; Filter [in_c, out_c, D, H, W]
    (same construction as the 2-D transpose rule in nn.py)."""
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    k_eff = [d[i] * (Filter.shape[2 + i] - 1) + 1 for i in range(3)]
    out = lax.conv_general_dilated(
        Input, jnp.flip(Filter, axis=(2, 3, 4)),
        window_strides=(1, 1, 1),
        padding=[(k_eff[i] - 1 - p[i], k_eff[i] - 1 - p[i]) for i in range(3)],
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=("NCDHW", "IODHW", "NCDHW"),
    )
    if Bias is not None:
        out = out + Bias.reshape((1, -1, 1, 1, 1))
    return {"Output": out}


@register_op("pool3d", propagate_seqlen=False)
def _pool3d(ctx, X):
    ptype = ctx.attr("pooling_type", "max")
    k = _triple(ctx.attr("ksize", [2, 2, 2]))
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(X, axis=(2, 3, 4), keepdims=True)}
    window = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(X.dtype, jnp.floating) \
            else jnp.iinfo(X.dtype).min
        return {"Out": lax.reduce_window(X, init, lax.max, window,
                                         strides, pads)}
    ssum = lax.reduce_window(X, 0.0, lax.add, window, strides, pads)
    if ctx.attr("exclusive", True):
        cnt = lax.reduce_window(jnp.ones_like(X), 0.0, lax.add, window,
                                strides, pads)
    else:
        cnt = float(np.prod(k))
    return {"Out": ssum / cnt}


@register_op("bilinear_interp", propagate_seqlen=False)
def _bilinear_interp(ctx, X, OutSize=None):
    """NCHW resize (reference bilinear_interp_op.cc). Static out shape from
    attrs (out_h/out_w or scale); OutSize tensors are unsupported under
    XLA's static-shape model — pass attrs instead."""
    if OutSize is not None:
        raise NotImplementedError(
            "dynamic OutSize breaks XLA static shapes; pass out_h/out_w attrs")
    n, c, h, w = X.shape
    scale = ctx.attr("scale", 0.0) or 0.0
    oh = ctx.attr("out_h", 0) or int(h * scale)
    ow = ctx.attr("out_w", 0) or int(w * scale)
    method = ctx.attr("interp_method", "bilinear")
    method = {"bilinear": "linear", "nearest": "nearest"}.get(method, method)
    out = jax.image.resize(X, (n, c, oh, ow), method=method)
    return {"Out": out.astype(X.dtype)}


@register_op("crop", propagate_seqlen=False)
def _crop(ctx, X, Y=None, Offsets=None):
    """Crop (reference crop_op.cc): output shape from attr or Y's shape
    (static); offsets from the attr or a runtime Offsets tensor — dynamic
    STARTS are a lax.dynamic_slice, fully XLA-legal."""
    shape = ctx.attr("shape") or (list(Y.shape) if Y is not None else None)
    if Offsets is not None:
        flat = Offsets.reshape(-1)
        if flat.shape[0] != X.ndim:   # reference enforces size == rank
            raise ValueError(
                f"crop: Offsets has {flat.shape[0]} elements for a "
                f"{X.ndim}-D input; one offset per dimension is required")
        starts = [flat[i].astype(jnp.int32) for i in range(X.ndim)]
        # NOTE divergence from the static-offsets branch: runtime offsets
        # that overflow CLAMP to the valid range (lax.dynamic_slice
        # semantics — a compiled program cannot raise on traced values);
        # the reference host-asserts offsets+shape <= dims. Validate on
        # the host when offsets come from untrusted input.
        return {"Out": lax.dynamic_slice(X, starts,
                                         [int(s) for s in shape])}
    offsets = ctx.attr("offsets") or [0] * X.ndim
    return {"Out": lax.slice(X, [int(o) for o in offsets],
                             [int(o) + int(s) for o, s in zip(offsets, shape)])}


@register_op("random_crop", needs_rng=True, propagate_seqlen=False)
def _random_crop(ctx, X):
    """Random spatial crop to attr `shape` (trailing dims, reference
    random_crop_op.cc). Offsets drawn per step from the functional PRNG."""
    shape = [int(s) for s in ctx.attr("shape")]
    lead = X.ndim - len(shape)
    maxs = [X.shape[lead + i] - shape[i] for i in range(len(shape))]
    keys = jax.random.split(ctx.key, len(shape))
    starts = [jnp.zeros((), jnp.int32)] * lead + [
        jax.random.randint(keys[i], (), 0, maxs[i] + 1)
        for i in range(len(shape))]
    sizes = list(X.shape[:lead]) + shape
    return {"Out": lax.dynamic_slice(X, starts, sizes)}


@register_op("label_smooth", propagate_seqlen=False)
def _label_smooth(ctx, X, PriorDist=None):
    eps = ctx.attr("epsilon", 0.1)
    k = X.shape[-1]
    prior = PriorDist if PriorDist is not None else 1.0 / k
    return {"Out": (1.0 - eps) * X + eps * prior}


@register_op("multiplex", propagate_seqlen=False)
def _multiplex(ctx, X, Ids):
    """Row-wise select among candidate tensors (reference multiplex_op.cc):
    out[i] = X[Ids[i]][i]."""
    stacked = jnp.stack(X if isinstance(X, list) else [X], axis=0)  # [K,B,..]
    ids = Ids.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return {"Out": stacked[ids, rows]}


@register_op("mean_iou", propagate_seqlen=False)
def _mean_iou(ctx, Predictions, Labels):
    """Mean intersection-over-union over classes (reference mean_iou_op.cc).
    Returns per-image-batch mean IoU plus the wrong/correct count vectors."""
    n = ctx.attr("num_classes")
    pred = Predictions.reshape(-1).astype(jnp.int32)
    lab = Labels.reshape(-1).astype(jnp.int32)
    onehot_p = jax.nn.one_hot(pred, n, dtype=jnp.float32)
    onehot_l = jax.nn.one_hot(lab, n, dtype=jnp.float32)
    inter = (onehot_p * onehot_l).sum(0)            # diag of confusion
    union = onehot_p.sum(0) + onehot_l.sum(0) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1e-9), 0.0)
    miou = iou.sum() / jnp.maximum(valid.sum(), 1)
    return {"OutMeanIou": miou.astype(jnp.float32),
            "OutWrong": (onehot_l.sum(0) - inter).astype(jnp.int32),
            "OutCorrect": inter.astype(jnp.int32)}


@register_op("roi_pool", propagate_seqlen=False)
def _roi_pool(ctx, X, ROIs, RoisLod=None):
    """Max-pool each ROI to a fixed grid (reference roi_pool_op.cc).

    ROIs: [N, 5] rows (batch_idx, x1, y1, x2, y2) in input-image
    coordinates. The reference loops ROIs in a CUDA kernel; here a vmap
    over ROIs computes each output bin as a masked max over the feature
    map — O(HW) per bin but static-shaped and fusible.
    """
    pooled_h = ctx.attr("pooled_height", 1)
    pooled_w = ctx.attr("pooled_width", 1)
    scale = ctx.attr("spatial_scale", 1.0)
    N, C, H, W = X.shape
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = jnp.round(roi[1] * scale), jnp.round(roi[2] * scale), \
            jnp.round(roi[3] * scale), jnp.round(roi[4] * scale)
        feat = X[b]                              # [C, H, W]
        rh = jnp.maximum(y2 - y1 + 1, 1.0) / pooled_h
        rw = jnp.maximum(x2 - x1 + 1, 1.0) / pooled_w
        def bin_val(ph, pw):
            hs = jnp.floor(y1 + ph * rh)
            he = jnp.ceil(y1 + (ph + 1) * rh)
            ws_ = jnp.floor(x1 + pw * rw)
            we = jnp.ceil(x1 + (pw + 1) * rw)
            m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                 & (xs[None, :] >= ws_) & (xs[None, :] < we))
            masked = jnp.where(m[None], feat, -jnp.inf)
            v = masked.max(axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)
        grid = jnp.stack([jnp.stack([bin_val(ph, pw)
                                     for pw in range(pooled_w)], -1)
                          for ph in range(pooled_h)], -2)
        return grid                               # [C, ph, pw]

    out = jax.vmap(one_roi)(ROIs.astype(jnp.float32))
    return {"Out": out.astype(X.dtype)}


@register_op("ctc_greedy_decoder", propagate_seqlen=True)
def _ctc_greedy_decoder(ctx, X, SeqLen=None):
    """Greedy CTC decode (reference ctc_align_op.cc semantics): argmax per
    frame, merge repeats, drop blanks. Output is a padded [B, T] id tensor
    plus decoded lengths via the @SEQLEN companion (the reference emits a
    LoD tensor)."""
    blank = ctx.attr("blank", 0)
    ids = jnp.argmax(X, axis=-1).astype(jnp.int32)       # [B, T]
    B, T = ids.shape
    seqlen = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    valid = jnp.arange(T)[None, :] < seqlen[:, None]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), ids[:, :-1]], 1)
    keep = valid & (ids != blank) & (ids != prev)
    # stable left-compaction: position of each kept token in the output
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((B, T), blank, jnp.int32)
    bidx = jnp.repeat(jnp.arange(B), T).reshape(B, T)
    out = out.at[bidx, jnp.where(keep, pos, T - 1)].set(
        jnp.where(keep, ids, blank), mode="drop")
    lens = keep.sum(axis=1).astype(jnp.int32)
    # re-blank any tail slot that a dropped write left dirty
    out = jnp.where(jnp.arange(T)[None, :] < lens[:, None], out, blank)
    return {"Out": out, "OutLen": lens}


@register_op("lod_reset", propagate_seqlen=False)
def _lod_reset(ctx, X, Y=None):
    """Replace X's sequence-length companion (reference lod_reset_op.cc).
    Y (or attr target_lod, offsets-style) provides the new lengths."""
    if Y is not None:
        lens = Y.astype(jnp.int32)
    else:
        lod = ctx.attr("target_lod")
        lens = jnp.asarray(np.diff(np.asarray(lod)), jnp.int32)
    if ctx.env is not None and ctx.op is not None:
        from ..core.ir import SEQLEN_SUFFIX
        for out_name in ctx.op.output("Out"):
            ctx.env[out_name + SEQLEN_SUFFIX] = lens
    return {"Out": X}


def _chunk_marks(tags, types, valid, scheme):
    """Exact chunk (begin, last) position masks per stream.

    A position is in a chunk iff its type >= 0 (B/I/E tags all belong to a
    chunk in these schemes). `begin` marks chunk starts, `last` marks chunk
    ends; a chunk is the [begin..last] run. Everything is computed from the
    local neighborhood, so the masks are exact (no end approximation)."""
    in_chunk = (types >= 0) & valid
    prev_in = jnp.concatenate([jnp.zeros_like(in_chunk[:, :1]),
                               in_chunk[:, :-1]], 1)
    prev_ty = jnp.concatenate([jnp.full_like(types[:, :1], -1),
                               types[:, :-1]], 1)
    prev_tag = jnp.concatenate([jnp.full_like(tags[:, :1], -1),
                                tags[:, :-1]], 1)
    if scheme == "IOB":      # tag 0=B, 1=I
        begin = in_chunk & ((tags == 0) | ~prev_in | (prev_ty != types))
    elif scheme == "IOE":    # tag 0=I, 1=E: E terminates a chunk
        begin = in_chunk & (~prev_in | (prev_ty != types) | (prev_tag == 1))
    elif scheme == "plain":
        begin = in_chunk & (~prev_in | (prev_ty != types))
    else:
        raise NotImplementedError(f"chunk scheme {scheme!r}")
    nxt_begin = jnp.concatenate([begin[:, 1:],
                                 jnp.zeros_like(begin[:, :1])], 1)
    nxt_in = jnp.concatenate([in_chunk[:, 1:],
                              jnp.zeros_like(in_chunk[:, :1])], 1)
    last = in_chunk & (nxt_begin | ~nxt_in)
    if scheme == "IOE":
        last = in_chunk & ((tags == 1) | nxt_begin | ~nxt_in)
    return begin, last


@register_op("chunk_eval", propagate_seqlen=False)
def _chunk_eval(ctx, X, Label, SeqLen=None):
    """Chunk precision/recall/F1 for NER-style tagging (reference
    chunk_eval_op.cc). A predicted chunk is correct iff a label chunk has
    the SAME begin, SAME end and SAME type — matched exactly via each
    stream's begin index at every chunk-last position."""
    num_types = ctx.attr("num_chunk_types")
    scheme = ctx.attr("chunk_scheme", "IOB")
    tag_num = {"IOB": 2, "IOE": 2, "plain": 1}[scheme]
    exclude = ctx.attr("excluded_chunk_types", []) or []

    def split(x):
        x = x.reshape(x.shape[0], -1).astype(jnp.int32)
        types = jnp.where(x >= 0, x // tag_num, -1)
        tags = jnp.where(x >= 0, x % tag_num, -1)
        oob = types >= num_types          # the "O"/outside tag
        return jnp.where(oob, -1, types), jnp.where(oob, -1, tags)

    def mask_excluded(types):
        m = jnp.ones_like(types, bool)
        for e in exclude:
            m &= types != e
        return m

    inf_ty, inf_tag = split(X)
    lab_ty, lab_tag = split(Label)
    B, T = inf_ty.shape
    seqlen = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    valid = jnp.arange(T)[None, :] < seqlen[:, None]

    inf_b, inf_l = _chunk_marks(inf_tag, inf_ty, valid, scheme)
    lab_b, lab_l = _chunk_marks(lab_tag, lab_ty, valid, scheme)
    inf_b &= mask_excluded(inf_ty)
    lab_b &= mask_excluded(lab_ty)
    inf_l &= mask_excluded(inf_ty)
    lab_l &= mask_excluded(lab_ty)

    # begin-index carried to every position: begins are strictly increasing
    # within a row, so a running max of (idx where begin else -1) gives the
    # begin of the chunk containing each in-chunk position exactly
    idx = jnp.arange(T, dtype=jnp.int32)[None, :]
    inf_cbi = jax.lax.cummax(jnp.where(inf_b, idx, -1), axis=1)
    lab_cbi = jax.lax.cummax(jnp.where(lab_b, idx, -1), axis=1)

    # chunk equality at shared last positions: same begin AND same type
    correct = (inf_l & lab_l & (inf_cbi == lab_cbi) & (inf_cbi >= 0)
               & (inf_ty == lab_ty))

    n_inf = inf_b.sum().astype(jnp.float32)
    n_lab = lab_b.sum().astype(jnp.float32)
    n_cor = correct.sum().astype(jnp.float32)
    precision = jnp.where(n_inf > 0, n_cor / jnp.maximum(n_inf, 1), 0.0)
    recall = jnp.where(n_lab > 0, n_cor / jnp.maximum(n_lab, 1), 0.0)
    f1 = jnp.where(n_cor > 0,
                   2 * precision * recall / jnp.maximum(precision + recall,
                                                        1e-9), 0.0)
    return {"NumInferChunks": inf_b.sum().astype(jnp.int32),
            "NumLabelChunks": lab_b.sum().astype(jnp.int32),
            "NumCorrectChunks": correct.sum().astype(jnp.int32),
            "Precision": precision, "Recall": recall, "F1-Score": f1}
