"""Fake-quantization ops (quantization-aware training / int8 inference).

Capability parity with reference paddle/fluid/operators/fake_quantize_op.cc
(abs_max / range_abs_max modes, bit_length attr, moving scale window) and
fake_dequantize_op.cc (max_abs mode), plus the contrib float16_transpiler
counterpart.

TPU-native notes: quantize-dequantize stays in float (the "fake" part, as
in the reference) so gradients flow with the straight-through estimator —
round() has zero gradient almost everywhere, so the rule re-expresses the
output as x + stop_gradient(q - x), the standard STE that the reference
realizes by simply not differentiating the op."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _ste(x, q):
    """Straight-through estimator: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


def _quant(x, scale, bin_cnt):
    s = jnp.maximum(scale, 1e-12)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * bin_cnt) * s / bin_cnt


@register_op("fake_quantize_abs_max", propagate_seqlen=False)
def _fake_quantize_abs_max(ctx, X):
    """dynamic per-tensor abs-max quantization (reference
    fake_quantize_op.cc quantize_type=abs_max)."""
    bits = int(ctx.attr("bit_length", 8))
    bin_cnt = (1 << (bits - 1)) - 1
    scale = jnp.max(jnp.abs(X))
    return {"Out": _ste(X, _quant(X, scale, bin_cnt)),
            "OutScale": scale.reshape(1)}


@register_op("fake_quantize_range_abs_max", propagate_seqlen=False)
def _fake_quantize_range_abs_max(ctx, X, InScale=None):
    """range_abs_max: in training, scale = max(running scale, batch
    abs-max) (windowed in the reference, fake_quantize_op.cc:73); at
    is_test the stored scale is used unchanged."""
    bits = int(ctx.attr("bit_length", 8))
    bin_cnt = (1 << (bits - 1)) - 1
    is_test = ctx.attr("is_test", False)
    cur = jnp.max(jnp.abs(X))
    if InScale is None:
        scale = cur
    elif is_test:
        scale = InScale.reshape(())
    else:
        scale = jnp.maximum(InScale.reshape(()), cur)
    return {"Out": _ste(X, _quant(X, scale, bin_cnt)),
            "OutScale": scale.reshape(1)}


@register_op("fake_dequantize_max_abs", propagate_seqlen=False)
def _fake_dequantize_max_abs(ctx, X, Scale):
    """reference fake_dequantize_op.cc: Out = X * Scale / max_range."""
    max_range = ctx.attr("max_range", 127.0)
    return {"Out": X * Scale.reshape(()) / max_range}
