"""Loss / metric op lowerings.

Capability parity with the reference loss family (reference:
paddle/fluid/operators/{cross_entropy_op.cc,softmax_with_cross_entropy_op.cc,
sigmoid_cross_entropy_with_logits_op.cc,squared_l2_distance_op.cc,
smooth_l1_loss_op.cc,huber_loss_op.cc,log_loss_op.cc,rank_loss_op.cc,
margin_rank_loss_op.cc,hinge_loss_op.cc,accuracy_op.cc,nce_op.cc,...}).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, register_grad
from ..core import types


def _squeeze_label(Label):
    if Label.ndim >= 2 and Label.shape[-1] == 1:
        return Label.reshape(Label.shape[:-1])
    return Label


@register_op("cross_entropy")
def _cross_entropy(ctx, X, Label):
    """X is a probability distribution (post-softmax), reference
    cross_entropy_op.cc semantics; output keeps a trailing 1-dim."""
    eps = 1e-8
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(Label * jnp.log(jnp.maximum(X, eps)), axis=-1, keepdims=True)
    else:
        ids = _squeeze_label(Label).astype(jnp.int32)
        p = jnp.take_along_axis(X, ids[..., None], axis=-1)
        ignore = ctx.attr("ignore_index", -100)
        loss = -jnp.log(jnp.maximum(p, eps))
        loss = jnp.where(ids[..., None] == ignore, 0.0, loss)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, Logits, Label):
    """Numerically-stable fused kernel (reference
    softmax_with_cross_entropy_op.cc). Outputs Softmax, Loss, and the
    log-sum-exp vector (hidden LSE output — the grad's residual). The
    hard-label loss reads only the gathered logit, so the full [rows, V]
    log-softmax never materializes unless the Softmax output is actually
    consumed (XLA DCEs it otherwise)."""
    logits32 = Logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1, keepdims=True)
    softmax = jnp.exp(logits32 - lse)
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(Label * (logits32 - lse), axis=-1, keepdims=True)
    else:
        ids = _squeeze_label(Label).astype(jnp.int32)
        picked = jnp.take_along_axis(logits32, ids[..., None], axis=-1)
        loss = lse - picked
        ignore = ctx.attr("ignore_index", -100)
        loss = jnp.where(ids[..., None] == ignore, 0.0, loss)
    return {"Softmax": softmax.astype(Logits.dtype),
            "Loss": loss.astype(Logits.dtype), "LSE": lse}


@register_grad("softmax_with_cross_entropy")
def _swce_grad(ctx, ins, out_grads):
    """Hand-written grad: dLogits = (softmax - onehot) * dLoss. The
    probabilities come from the SAVED Softmax forward output when the
    lowerer provides it (reference softmax_with_cross_entropy_op grad
    consumes Softmax the same way) — the backward is then pure
    elementwise and fuses into the grad matmul's operand, instead of
    re-running the max/sum reductions over the [B*T, V] logits (round-4
    profile: the recompute cost ~3 ms/step as standalone reduce fusions).
    Falls back to recomputation when the saved output is unavailable.
    Never asks jax.vjp to save an f32 probabilities residual — 2 GB at
    (64,256,30k), the allocation that OOM'd batch 256 in round 3."""
    Logits, Label = ins["Logits"][0], ins["Label"][0]
    gL = out_grads.get("Loss", [None])[0]
    gS = out_grads.get("Softmax", [None])[0]
    fwd_outs = getattr(ctx, "fwd_outs", {})
    saved_lse = fwd_outs.get("LSE", [None])[0]
    saved_sm = fwd_outs.get("Softmax", [None])[0]
    if saved_lse is not None:
        # preferred: the [rows, 1] f32 lse residual — softmax rebuilds as
        # exp(logits - lse), pure elementwise, fusing into the dLogits
        # consumers; no [rows, V] reduction re-runs in the backward and
        # no [rows, V] tensor crosses the fwd/bwd boundary
        logits32 = Logits.astype(jnp.float32)
        lse = saved_lse
        softmax = jnp.exp(logits32 - lse)
    elif saved_sm is not None and saved_sm.dtype != jnp.float32:
        # reference grad convention (consume the saved Softmax output) —
        # but only in a half dtype: a live f32 [rows, V] residual is the
        # 2 GB allocation that OOM'd batch 256 in round 3
        softmax = saved_sm.astype(jnp.float32)
        logits32 = lse = None
    else:
        logits32 = Logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits32, axis=-1, keepdims=True)
        softmax = jnp.exp(logits32 - lse)       # fused into the consumers
    d = jnp.zeros_like(softmax)
    soft_label = ctx.attr("soft_label", False)
    d_label = None
    if soft_label and jnp.issubdtype(Label.dtype, jnp.floating):
        # always materialize the Label cotangent: backward.py may have
        # declared Label@GRAD even when only the Softmax output is used
        d_label = jnp.zeros(Label.shape, Label.dtype)
    if soft_label and logits32 is None:
        # the Label cotangent needs log_softmax — recompute from logits
        # (soft-label is off the hot transformer path)
        logits32 = Logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits32, axis=-1, keepdims=True)
    if gL is not None:
        gL32 = gL.astype(jnp.float32)
        if soft_label:
            lab32 = Label.astype(jnp.float32)
            # exact: d/dLogits[-sum(L*log_softmax)] = sum(L)*softmax - L
            # (reduces to softmax - L only when rows sum to 1; unnormalized
            # soft targets are legal inputs and the vjp this replaces was
            # exact for them)
            lsum = jnp.sum(lab32, axis=-1, keepdims=True)
            d = d + (lsum * softmax - lab32) * gL32
            d_label = (-(logits32 - lse) * gL32).astype(Label.dtype)
        else:
            ids = _squeeze_label(Label).astype(jnp.int32)
            onehot = (ids[..., None]
                      == jnp.arange(softmax.shape[-1], dtype=jnp.int32))
            contrib = (softmax - onehot.astype(jnp.float32)) * gL32
            ignore = ctx.attr("ignore_index", -100)
            contrib = jnp.where(ids[..., None] == ignore, 0.0, contrib)
            d = d + contrib
    if gS is not None:
        gS32 = gS.astype(jnp.float32)
        inner = gS32 - jnp.sum(gS32 * softmax, axis=-1, keepdims=True)
        d = d + softmax * inner
    out = {"Logits": d.astype(Logits.dtype)}
    if d_label is not None:
        out["Label"] = d_label
    return out


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, X, Label):
    loss = jnp.maximum(X, 0.0) - X * Label + jnp.log1p(jnp.exp(-jnp.abs(X)))
    ignore = ctx.attr("ignore_index", -100)
    loss = jnp.where(Label == ignore, 0.0, loss)
    return {"Out": loss}


@register_op("square_error_cost")
def _square_error_cost(ctx, X, Y):
    d = X - Y
    return {"Out": d * d}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, X, Y, InsideWeight=None, OutsideWeight=None):
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    d = X - Y
    if InsideWeight is not None:
        d = d * InsideWeight
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    if OutsideWeight is not None:
        loss = loss * OutsideWeight
    loss = jnp.sum(loss.reshape(loss.shape[0], -1), axis=-1, keepdims=True)
    return {"Out": loss, "Diff": d}


@register_op("huber_loss")
def _huber(ctx, X, Y):
    delta = ctx.attr("delta", 1.0)
    d = Y - X
    ad = jnp.abs(d)
    loss = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    return {"Out": loss, "Residual": d}


@register_op("log_loss")
def _log_loss(ctx, Predicted, Labels):
    eps = ctx.attr("epsilon", 1e-4)
    p = Predicted
    return {"Loss": -Labels * jnp.log(p + eps) - (1 - Labels) * jnp.log(1 - p + eps)}


@register_op("rank_loss")
def _rank_loss(ctx, Label, Left, Right):
    d = Left - Right
    return {"Out": jnp.log1p(jnp.exp(d)) - Label * d}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, Label, X1, X2):
    margin = ctx.attr("margin", 0.0)
    act = jnp.maximum(0.0, -Label * (X1 - X2) + margin)
    return {"Out": act, "Activated": (act > 0).astype(X1.dtype)}


@register_op("hinge_loss")
def _hinge_loss(ctx, Logits, Labels):
    y = Labels * 2.0 - 1.0
    return {"Loss": jnp.maximum(0.0, 1.0 - y * Logits)}


@register_op("accuracy", propagate_seqlen=False)
def _accuracy(ctx, Out, Indices, Label):
    """Top-k accuracy (reference accuracy_op.cc): Indices [N,k] from top_k."""
    label = _squeeze_label(Label).astype(types.index_dtype())
    correct = jnp.any(Indices == label[:, None], axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.int32(label.shape[0])
    acc = num_correct.astype(jnp.float32) / jnp.float32(label.shape[0])
    return {"Accuracy": acc.reshape((1,)), "Correct": num_correct.reshape((1,)),
            "Total": total.reshape((1,))}


@register_op("auc", propagate_seqlen=False)
def _auc(ctx, Predict, Label, StatPos, StatNeg):
    """Streaming AUC via threshold buckets (reference auc_op.cc)."""
    num_thresholds = ctx.attr("num_thresholds", 200)
    pos_prob = Predict[:, 1] if Predict.ndim == 2 and Predict.shape[1] == 2 else Predict.reshape(-1)
    label = _squeeze_label(Label).astype(jnp.float32).reshape(-1)
    idx = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0, num_thresholds)
    pos = StatPos.at[idx].add(label)
    neg = StatNeg.at[idx].add(1.0 - label)
    # trapezoid over descending thresholds
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    auc = jnp.trapezoid(tpr, fpr)
    return {"AUC": auc.reshape((1,)), "StatPosOut": pos, "StatNegOut": neg}
