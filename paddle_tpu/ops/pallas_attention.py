"""Fused (flash) attention: Pallas TPU kernels + ring-attention building block.

The reference's only attention is an unfused softmax(QK^T)V composition
(reference: python/paddle/fluid/nets.py:329 scaled_dot_product_attention).
TPU-native redesign: Pallas kernels stream K/V blocks through VMEM with an
online-softmax accumulator, so the [T, T] score matrix never materializes in
HBM — O(T) memory instead of O(T^2) in both forward AND backward (the
backward kernels recompute attention weights from the saved logsumexp, the
FlashAttention-2 scheme: one kernel for dQ gridded over query blocks, one for
dK/dV gridded over key blocks).

Attention-weight dropout runs inside the kernel using the TPU PRNG
(pltpu.prng_seed / prng_random_bits), re-seeded per (batch·head, q-block,
k-block) so forward and both backward kernels regenerate identical masks in
any iteration order.

Off-TPU the same kernels run under the Pallas interpreter when
PADDLE_TPU_PALLAS_INTERPRET=1 (used by the CPU test suite); otherwise a
pure-jnp reference path takes over.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op

NEG_INF = -1e30


def _blk(T):
    """Block size: biggest power-of-two tile <= 256 dividing T. Larger tiles
    amortize per-program overhead; 256x256 f32 scores tiles fit VMEM easily."""
    for b in (256, 128):
        if T % b == 0:
            return b
    raise ValueError(f"flash attention needs T % 128 == 0, got {T}")


def _interpret():
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


# ---------------------------------------------------------------------------
# reference jnp implementation (off-TPU fallback)
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, causal, sm_scale, dropout_rate=0.0,
                         seed=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Tq)[:, None]
        col = jnp.arange(Tk)[None, :]
        s = jnp.where(col > row, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate:
        key = jax.random.key(seed if seed is not None else 0)
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------

# multiplicative-hash constants (Knuth), expressed as python ints that fit
# int32 so Mosaic folds them; applied in two rounds so adjacent tile indices
# land on well-separated PRNG streams.
_HASH_A = int(np.int32(np.uint32(2654435761)))
_HASH_B = 40503


def _dropout_mask(seed_ref, bh, qi, kj, shape, rate):
    """Deterministic keep-mask for one (bh, q-block, k-block) tile. Re-seeding
    per tile makes the mask independent of kernel iteration order, so the
    forward, dQ and dK/dV kernels all regenerate the same mask."""
    from jax.experimental.pallas import tpu as pltpu

    s = seed_ref[0, 0] * _HASH_A + bh * _HASH_B + qi
    s = s * _HASH_A + kj
    pltpu.prng_seed(s)
    bits = pltpu.prng_random_bits(shape)  # uniform int32 over full range
    # P(bits >= t) = 1 - rate  for t = -2^31 + rate * 2^32
    thresh = int(min(max(-2**31 + rate * 2**32, -2**31), 2**31 - 1))
    return bits >= jnp.int32(thresh)


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      sm_scale, causal, blk_k, dropout_rate):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    T = k_ref.shape[1]
    D = q_ref.shape[2]
    blk_q = q_ref.shape[1]
    nblk = T // blk_k

    q = q_ref[0].astype(jnp.float32) * sm_scale        # [blk_q, D]

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            row = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            col = j * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col > row, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, qi, j, (blk_q, blk_k),
                                 dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_new = acc * alpha[:, None] + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((blk_q,), jnp.float32)
    acc0 = jnp.zeros((blk_q, D), jnp.float32)
    if causal:
        hi = (qi * blk_q) // blk_k + (blk_q + blk_k - 1) // blk_k
        hi = jnp.minimum(hi, nblk)
    else:
        hi = nblk
    m, l, acc = lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, pl.dslice(qi * blk_q, blk_q)] = m + jnp.log(l)


def _flash_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, *, sm_scale, causal, blk_k,
                     dropout_rate):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    T = k_ref.shape[1]
    blk_q = q_ref.shape[1]
    nblk = T // blk_k

    q = q_ref[0].astype(jnp.float32) * sm_scale
    do = do_ref[0].astype(jnp.float32)                 # [blk_q, D]
    lse = lse_ref[0, 0, pl.dslice(qi * blk_q, blk_q)]  # [blk_q]
    delta = delta_ref[0, 0, pl.dslice(qi * blk_q, blk_q)]

    def body(j, acc):
        k = k_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            row = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            col = j * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col > row, NEG_INF, s)
        w = jnp.exp(s - lse[:, None])                  # normalized weights
        dpv = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, qi, j, (blk_q, blk_k),
                                 dropout_rate)
            dw = jnp.where(keep, dpv / (1.0 - dropout_rate), 0.0)
        else:
            dw = dpv
        ds = w * (dw - delta[:, None])
        return acc + lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    if causal:
        hi = (qi * blk_q) // blk_k + (blk_q + blk_k - 1) // blk_k
        hi = jnp.minimum(hi, nblk)
    else:
        hi = nblk
    acc0 = jnp.zeros((blk_q, q_ref.shape[2]), jnp.float32)
    acc = lax.fori_loop(0, hi, body, acc0)
    # s = sm_scale * (q . k)  =>  dq = sm_scale * ds @ k
    dq_ref[0] = (acc * sm_scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, *, sm_scale, causal, blk_q,
                      dropout_rate):
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    kj = pl.program_id(1)
    T = q_ref.shape[1]
    D = q_ref.shape[2]
    blk_k = k_ref.shape[1]
    nblk = T // blk_q

    k = k_ref[0].astype(jnp.float32)                   # [BLK_K, D]
    v = v_ref[0].astype(jnp.float32)

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.dslice(i * blk_q, blk_q), :].astype(jnp.float32) \
            * sm_scale
        do = do_ref[0, pl.dslice(i * blk_q, blk_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(i * blk_q, blk_q)]
        delta = delta_ref[0, 0, pl.dslice(i * blk_q, blk_q)]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            row = i * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            col = kj * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(col > row, NEG_INF, s)
        w = jnp.exp(s - lse[:, None])                  # [blk_q, BLK_K]
        dpv = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, i, kj, (blk_q, blk_k),
                                 dropout_rate)
            w_drop = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
            dw = jnp.where(keep, dpv / (1.0 - dropout_rate), 0.0)
        else:
            w_drop, dw = w, dpv
        dv_new = dv_acc + lax.dot_general(
            w_drop, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = w * (dw - delta[:, None])
        dk_new = dk_acc + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    if causal:
        lo = (kj * blk_k) // blk_q
    else:
        lo = 0
    z = jnp.zeros((blk_k, D), jnp.float32)
    dk, dv = lax.fori_loop(lo, nblk, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)  # q pre-scaled => includes sm_scale
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _seed_arr(seed):
    return jnp.asarray(seed, jnp.int32).reshape(1, 1)


def _flash_forward(q, k, v, causal, sm_scale, dropout_rate=0.0, seed=0):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    BQ = BK = _blk(T)
    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)
    grid = (B * H, T // BQ)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, blk_k=BK,
                               dropout_rate=dropout_rate)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, qi: (0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BQ, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, qi: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3)
    return out.reshape(B, H, T, D), lse


def _flash_backward(q, k, v, o, lse, g, causal, sm_scale, dropout_rate, seed):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    q3, k3, v3 = (x.reshape(B * H, T, D) for x in (q, k, v))
    o3 = o.reshape(B * H, T, D)
    g3 = g.reshape(B * H, T, D)
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]

    BQ = BK = _blk(T)
    dq_kernel = functools.partial(_flash_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, blk_k=BK,
                                  dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, T // BQ),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, qi: (0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3, g3, lse, delta)

    dkv_kernel = functools.partial(_flash_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, blk_q=BQ,
                                   dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, T // BK),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, kj: (0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, T, D), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, kj: (bh, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda bh, kj: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda bh, kj: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj: (bh, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3, g3, lse, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _pallas_ok(q, dropout_rate=0.0):
    if jax.default_backend() == "cpu" and not _interpret():
        return False
    B, H, T, D = q.shape
    if _interpret() and dropout_rate:
        return False  # pltpu.prng_* has no interpreter implementation
    return T % 128 == 0 and D <= 256


# ---------------------------------------------------------------------------
# public entry: custom_vjp so program autodiff gets the Pallas backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, seed, causal=False, sm_scale=1.0,
                    dropout_rate=0.0):
    """seed: int32 scalar (traced) driving attention-weight dropout."""
    if _pallas_ok(q, dropout_rate):
        out, _ = _flash_forward(q, k, v, causal, sm_scale, dropout_rate, seed)
        return out
    return _attention_reference(q, k, v, causal, sm_scale, dropout_rate, seed)


def _fa_fwd(q, k, v, seed, causal, sm_scale, dropout_rate):
    if _pallas_ok(q, dropout_rate):
        out, lse = _flash_forward(q, k, v, causal, sm_scale, dropout_rate,
                                  seed)
        return out, (q, k, v, out, lse, seed)
    out = _attention_reference(q, k, v, causal, sm_scale, dropout_rate, seed)
    return out, (q, k, v, None, None, seed)


def _fa_bwd(causal, sm_scale, dropout_rate, res, g):
    q, k, v, o, lse, seed = res
    if o is not None:
        dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal, sm_scale,
                                     dropout_rate, seed)
    else:
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale,
                                                 dropout_rate, seed),
            q, k, v)
        dq, dk, dv = vjp(g)
    dseed = np.zeros(jnp.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dseed


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@register_op("fused_attention", propagate_seqlen=False, needs_rng=True)
def _fused_attention(ctx, Q, K, V):
    """Q/K/V: [B, H, T, Dh]. attrs: causal, sm_scale, dropout_rate, is_test.

    Replaces the reference's matmul+softmax+dropout+matmul composition
    (nets.py:329) with one O(T)-memory kernel. Dropout is applied to the
    attention weights inside the kernel, keyed from the executor's
    functional PRNG."""
    sm_scale = ctx.attr("sm_scale", 1.0 / math.sqrt(Q.shape[-1]))
    causal = ctx.attr("causal", False)
    rate = 0.0 if ctx.attr("is_test", False) else ctx.attr("dropout_rate", 0.0)
    mesh = getattr(ctx.lowerer, "mesh", None) if ctx.lowerer else None
    if (mesh is not None and "sp" in mesh.axis_names
            and mesh.shape["sp"] > 1):
        # sequence parallelism: the ParallelExecutor shards the seq dim
        # over 'sp', so attention becomes Ring Attention — K/V shards
        # rotate over ICI while the online softmax accumulates.
        if rate:
            raise NotImplementedError(
                "attention-weight dropout is not supported under sequence "
                "parallelism; build the model with dropout_rate=0 (or move "
                "dropout outside the attention op)")
        if Q.shape[2] % mesh.shape["sp"] != 0:
            raise ValueError(
                f"sequence length {Q.shape[2]} is not divisible by the "
                f"{mesh.shape['sp']}-way 'sp' mesh axis; pad the sequence "
                f"or choose an sp that divides it")
        return {"Out": ring_attention(Q, K, V, mesh, axis="sp",
                                      causal=causal, sm_scale=sm_scale)}
    seed = jnp.uint32(0)
    if rate and ctx.key is not None:
        seed = jax.random.key_data(ctx.key).reshape(-1)[0]
    return {"Out": flash_attention(Q, K, V, seed.astype(jnp.int32), causal,
                                   sm_scale, float(rate))}


# ---------------------------------------------------------------------------
# ring attention: sequence parallelism over an 'sp' mesh axis
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, mesh, axis="sp", causal=False, sm_scale=None):
    """Exact attention with Q/K/V sequence-sharded over `axis`.

    Each device holds a [B, H, T/sp, D] shard; K/V shards rotate around the
    ring via ppermute while a running online-softmax (m, l, acc) accumulates
    — the Ring Attention algorithm. Communication rides ICI neighbor links;
    peak memory per chip is O(T/sp). Built from differentiable jax ops
    (ppermute has a transpose rule), so training works through it.

    Exceeds reference capability: the reference has no sequence parallelism
    (SURVEY.md §5.7).
    """
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    sp = mesh.shape[axis]

    def local(qs, ks, vs):
        idx = lax.axis_index(axis)
        Tl = qs.shape[2]

        def block(carry, chunk_i):
            m, l, acc, kc, vc = carry
            # which global chunk do we currently hold?
            src = (idx - chunk_i) % sp
            s = jnp.einsum("bhqd,bhkd->bhqk", qs, kc).astype(jnp.float32) \
                * sm_scale
            if causal:
                row = (idx * Tl + jnp.arange(Tl))[:, None]
                col = (src * Tl + jnp.arange(Tl))[None, :]
                s = jnp.where(col[None, None] > row[None, None], NEG_INF, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc).astype(jnp.float32)
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (m_new, l_new, acc_new, kc, vc), None

        B, H, _, D = qs.shape
        m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, Tl), jnp.float32)
        acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
        (m, l, acc, _, _), _ = lax.scan(block, (m0, l0, acc0, ks, vs),
                                        jnp.arange(sp))
        return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(qs.dtype)

    # carry the mesh's OTHER axes in the specs too: naming only 'sp' would
    # make GSPMD all-gather the full batch/head dims into every dp/mp
    # group and compute attention redundantly across them
    names = mesh.axis_names
    b_ax = "dp" if ("dp" in names and q.shape[0] % mesh.shape["dp"] == 0) \
        else None
    h_ax = "mp" if ("mp" in names and q.shape[1] % mesh.shape["mp"] == 0) \
        else None
    spec = P(b_ax, h_ax, axis, None)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
