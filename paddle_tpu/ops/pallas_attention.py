"""Fused (flash) attention: Pallas TPU kernel + ring-attention building block.

The reference's only attention is an unfused softmax(QK^T)V composition
(reference: python/paddle/fluid/nets.py:329 scaled_dot_product_attention).
TPU-native redesign: a Pallas kernel streams K/V blocks through VMEM with an
online-softmax accumulator, so the [T, T] score matrix never materializes in
HBM — O(T) memory instead of O(T^2), which is what makes long-context
training feasible. Falls back to a pure-jnp path off-TPU / for odd shapes.

Backward currently recomputes attention via the jnp reference under
custom_vjp (correct; the dedicated backward kernel is a planned
optimization).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

BLK_Q = 128
BLK_K = 128
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# reference jnp implementation (used off-TPU and for the backward pass)
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, causal, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Tq)[:, None]
        col = jnp.arange(Tk)[None, :]
        s = jnp.where(col > row, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, blk_k):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    T = k_ref.shape[1]
    D = q_ref.shape[2]
    nblk = T // blk_k

    q = q_ref[0].astype(jnp.float32) * sm_scale        # [BLK_Q, D]

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.dslice(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            row = qi * BLK_Q + jax.lax.broadcasted_iota(jnp.int32,
                                                        (BLK_Q, blk_k), 0)
            col = j * blk_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (BLK_Q, blk_k), 1)
            s = jnp.where(col > row, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((BLK_Q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((BLK_Q,), jnp.float32)
    acc0 = jnp.zeros((BLK_Q, D), jnp.float32)
    if causal:
        hi = (qi * BLK_Q) // blk_k + (BLK_Q + blk_k - 1) // blk_k
        hi = jnp.minimum(hi, nblk)
    else:
        hi = nblk
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, sm_scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)
    grid = (B * H, T // BLK_Q)
    kernel = functools.partial(_flash_kernel, sm_scale=sm_scale,
                               causal=causal, blk_k=BLK_K)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLK_Q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLK_Q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
    )(q3, k3, v3)
    return out.reshape(B, H, T, D)


def _pallas_ok(q):
    if jax.default_backend() == "cpu":
        return False
    B, H, T, D = q.shape
    return T % BLK_Q == 0 and T % BLK_K == 0 and D <= 256


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, sm_scale=1.0):
    if _pallas_ok(q):
        return _flash_forward(q, k, v, causal, sm_scale)
    return _attention_reference(q, k, v, causal, sm_scale)


def _fa_fwd(q, k, v, causal, sm_scale):
    return flash_attention(q, k, v, causal, sm_scale), (q, k, v)


def _fa_bwd(causal, sm_scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _attention_reference(a, b, c, causal,
                                                          sm_scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@register_op("fused_attention", propagate_seqlen=False)
def _fused_attention(ctx, Q, K, V):
    """Q/K/V: [B, H, T, Dh]. attrs: causal, sm_scale."""
    sm_scale = ctx.attr("sm_scale", 1.0 / math.sqrt(Q.shape[-1]))
    causal = ctx.attr("causal", False)
    return {"Out": flash_attention(Q, K, V, causal, sm_scale)}


# ---------------------------------------------------------------------------
# ring attention: sequence parallelism over an 'sp' mesh axis
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, mesh, axis="sp", causal=False, sm_scale=None):
    """Exact attention with Q/K/V sequence-sharded over `axis`.

    Each device holds a [B, H, T/sp, D] shard; K/V shards rotate around the
    ring via ppermute while a running online-softmax (m, l, acc) accumulates
    — the Ring Attention algorithm. Communication rides ICI neighbor links;
    peak memory per chip is O(T/sp). Built from differentiable jax ops
    (ppermute has a transpose rule), so training works through it.

    Exceeds reference capability: the reference has no sequence parallelism
    (SURVEY.md §5.7).
    """
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    sp = mesh.shape[axis]

    def local(qs, ks, vs):
        idx = lax.axis_index(axis)
        Tl = qs.shape[2]

        def block(carry, chunk_i):
            m, l, acc, kc, vc = carry
            # which global chunk do we currently hold?
            src = (idx - chunk_i) % sp
            s = jnp.einsum("bhqd,bhkd->bhqk", qs, kc).astype(jnp.float32) \
                * sm_scale
            if causal:
                row = (idx * Tl + jnp.arange(Tl))[:, None]
                col = (src * Tl + jnp.arange(Tl))[None, :]
                s = jnp.where(col[None, None] > row[None, None], NEG_INF, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc).astype(jnp.float32)
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (m_new, l_new, acc_new, kc, vc), None

        B, H, _, D = qs.shape
        m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, Tl), jnp.float32)
        acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
        (m, l, acc, _, _), _ = lax.scan(block, (m0, l0, acc0, ks, vs),
                                        jnp.arange(sp))
        return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(qs.dtype)

    spec = P(None, None, axis, None)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)(q, k, v)
