"""Fused (flash) attention: Pallas TPU kernels + ring-attention building block.

The reference's only attention is an unfused softmax(QK^T)V composition
(reference: python/paddle/fluid/nets.py:329 scaled_dot_product_attention).
TPU-native redesign: Pallas kernels stream K/V blocks through VMEM with an
online-softmax accumulator, so the [T, T] score matrix never materializes in
HBM — O(T) memory instead of O(T^2) in both forward AND backward (the
backward kernels recompute attention weights from the saved logsumexp, the
FlashAttention-2 scheme: one kernel for dQ gridded over query blocks, one for
dK/dV gridded over key blocks).

Attention-weight dropout runs inside the kernel using the TPU PRNG
(pltpu.prng_seed / prng_random_bits), re-seeded per (batch·head, q-block,
k-block) so forward and both backward kernels regenerate identical masks in
any iteration order.

Off-TPU the same kernels run under the Pallas interpreter when
PADDLE_TPU_PALLAS_INTERPRET=1 (used by the CPU test suite); otherwise a
pure-jnp reference path takes over.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.registry import register_op

NEG_INF = -1e30


# sweep override: (BQ, BK) or None -> tuned default (tools/flash_probe.py)
_BLOCK_OVERRIDE = None


# Per-(seq, causal) tuned tiles, round-5 chained sweeps on v5e at D=64
# (tools/flash_block_sweep.py, docs/PERF.md): wide streamed-K blocks win
# every non-causal shape measured — (512, 2048) is +10% over 1024^2 at
# 2048/4096 and +13% at 8192 — while causal keeps 1024^2 at >=4096
# (ties at 8192, loses at 4096) and takes (256, 2048) at 2048 (+27%:
# the whole K/V row sits in one block, so the mask applies in-register
# instead of paying per-block grid iterations). Non-causal T >= 2048
# generalizes the measured pattern; other shapes fall back to the
# biggest power-of-two tile <= 1024 dividing T.
_BLOCK_TABLE = {
    (2048, True): (256, 2048),
}


def _table_blk(T, causal):
    tbl = _BLOCK_TABLE.get((int(T), bool(causal)))
    if tbl is not None:
        return tbl
    if not causal and T >= 2048 and T % 2048 == 0:
        return (512, 2048)
    return None


def _blk(T, causal=False):
    """Block sizes (BQ, BK) for sequence length T. Tuned by the chained
    sweeps on v5e (tools/flash_block_sweep.py, docs/PERF.md): the
    per-(seq, causal) table above where measured, else the biggest
    power-of-two tile <= 1024 dividing T (the round-4 reproducible
    winner; bigger streamed BK means fewer sequential grid steps to
    pipeline). Since the kernels stream K/V (resp. Q) through the grid's
    innermost dimension, VMEM per program is O(blk_q * blk_k + blk * D)
    regardless of T — no sequence-length cap (validated to seq 32768)."""
    if _BLOCK_OVERRIDE is not None:
        bq, bk = _BLOCK_OVERRIDE
        if T % bq == 0 and T % bk == 0:
            return bq, bk
    tbl = _table_blk(T, causal)
    if tbl is not None and T % tbl[0] == 0 and T % tbl[1] == 0:
        return tbl
    for b in (1024, 512, 256, 128):
        if T % b == 0:
            return b, b
    raise ValueError(f"flash attention needs T % 128 == 0, got {T}")


def _interpret():
    return os.environ.get("PADDLE_TPU_PALLAS_INTERPRET", "0") == "1"


# ---------------------------------------------------------------------------
# reference jnp implementation (off-TPU fallback)
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, causal, sm_scale, dropout_rate=0.0,
                         seed=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        row = jnp.arange(Tq)[:, None]
        col = jnp.arange(Tk)[None, :]
        s = jnp.where(col > row, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate:
        key = jax.random.key(seed if seed is not None else 0)
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------

# multiplicative-hash constants (Knuth), expressed as python ints that fit
# int32 so Mosaic folds them; applied in two rounds so adjacent tile indices
# land on well-separated PRNG streams.
_HASH_A = int(np.int32(np.uint32(2654435761)))
_HASH_B = 40503


def _causal_live(qi, kj, blk_q, blk_k):
    """Whether the (qi, kj) block intersects the causal lower triangle.
    Shared by all three kernels — block coverage and dropout-mask seeding
    are keyed to the same (qi, kj) indices, so the fwd/dQ/dKV predicates
    must be structurally identical."""
    return kj * blk_k <= qi * blk_q + blk_q - 1


def _apply_causal_mask(s, qi, kj, blk_q, blk_k):
    """Mask strictly-above-diagonal entries of one score tile."""
    row = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    col = kj * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return jnp.where(col > row, NEG_INF, s)


def _dropout_mask(seed_ref, bh, qi, kj, shape, rate):
    """Deterministic keep-mask for one (bh, q-block, k-block) tile. Re-seeding
    per tile makes the mask independent of kernel iteration order, so the
    forward, dQ and dK/dV kernels all regenerate the same mask."""
    from jax.experimental.pallas import tpu as pltpu

    s = seed_ref[0, 0] * _HASH_A + bh * _HASH_B + qi
    s = s * _HASH_A + kj
    pltpu.prng_seed(s)
    bits = pltpu.prng_random_bits(shape)  # uniform int32 over full range
    # P(bits >= t) = 1 - rate  for t = -2^31 + rate * 2^32
    thresh = int(min(max(-2**31 + rate * 2**32, -2**31), 2**31 - 1))
    return bits >= jnp.int32(thresh)


def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_sc, l_sc, acc_sc, *,
                      sm_scale, causal, dropout_rate):
    """K/V STREAM through the grid's innermost ("arbitrary") dimension:
    each program sees one [blk_k, D] K/V block, with the online-softmax
    state carried in VMEM scratch across kj iterations. VMEM per program
    is O(blk_q * (blk_k + D)) regardless of T — the previous full-K/V
    residency capped T*D (scoped-VMEM OOM at seq 8192 with D=128)."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    blk_q = q_ref.shape[1]
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # causal: blocks entirely above the diagonal contribute nothing
    live = _causal_live(qi, kj, blk_q, blk_k) if causal else True

    @pl.when(live)
    def _update():
        # dots run in the INPUT dtype (bf16 under AMP -> full MXU rate;
        # the round-3 kernels upcast to f32 first, quartering matmul
        # throughput) with f32 accumulation via preferred_element_type;
        # sm_scale is applied to the f32 product so no operand precision
        # is spent on it
        q = q_ref[0]                                   # [blk_q, D]
        k = k_ref[0]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi, kj, blk_q, blk_k)
        m = m_sc[...]
        l = l_sc[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, qi, kj, (blk_q, blk_k),
                                 dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_sc[...] = acc_sc[...] * alpha[:, None] + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[...] = m_new
        l_sc[...] = l_new

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_sc[...], 1e-20)
        o_ref[0] = (acc_sc[...] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[...] + jnp.log(l)


def _flash_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, dq_sc, *, sm_scale, causal,
                     dropout_rate):
    """dQ with K/V streamed through the innermost grid dim (see
    _flash_fwd_kernel); the dQ accumulator lives in VMEM scratch."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    blk_q = q_ref.shape[1]
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    live = _causal_live(qi, kj, blk_q, blk_k) if causal else True

    @pl.when(live)
    def _update():
        q = q_ref[0]
        do = do_ref[0]                                 # [blk_q, D]
        lse = lse_ref[0, 0]                            # [blk_q]
        delta = delta_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi, kj, blk_q, blk_k)
        w = jnp.exp(s - lse[:, None])                  # normalized weights
        dpv = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, qi, kj, (blk_q, blk_k),
                                 dropout_rate)
            dw = jnp.where(keep, dpv / (1.0 - dropout_rate), 0.0)
        else:
            dw = dpv
        ds = w * (dw - delta[:, None]) * sm_scale
        dq_sc[...] = dq_sc[...] + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *,
                      sm_scale, causal, dropout_rate):
    """dK/dV with Q/dOut/lse/delta streamed through the innermost grid
    dim (grid = (BH, kj, qi)); accumulators in VMEM scratch."""
    from jax.experimental import pallas as pl

    bh = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    blk_q = q_ref.shape[1]
    blk_k = k_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    # causal: q blocks strictly above this k block see none of it
    live = _causal_live(qi, kj, blk_q, blk_k) if causal else True

    @pl.when(live)
    def _update():
        k = k_ref[0]                                   # [blk_k, D]
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _apply_causal_mask(s, qi, kj, blk_q, blk_k)
        w = jnp.exp(s - lse[:, None])                  # [blk_q, blk_k]
        dpv = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        if dropout_rate:
            keep = _dropout_mask(seed_ref, bh, qi, kj, (blk_q, blk_k),
                                 dropout_rate)
            w_drop = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
            dw = jnp.where(keep, dpv / (1.0 - dropout_rate), 0.0)
        else:
            w_drop, dw = w, dpv
        dv_sc[...] = dv_sc[...] + lax.dot_general(
            w_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = w * (dw - delta[:, None]) * sm_scale
        dk_sc[...] = dk_sc[...] + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _seed_arr(seed):
    return jnp.asarray(seed, jnp.int32).reshape(1, 1)


def _compiler_params():
    """Innermost grid dim iterates sequentially (it carries the scratch
    accumulators); the outer two are parallel."""
    from jax.experimental.pallas import tpu as pltpu
    try:
        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except AttributeError:  # older jax naming
        return pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))


def _flash_forward(q, k, v, causal, sm_scale, dropout_rate=0.0, seed=0):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    BQ, BK = _blk(T, causal)
    q3 = q.reshape(B * H, T, D)
    k3 = k.reshape(B * H, T, D)
    v3 = v.reshape(B * H, T, D)
    grid = (B * H, T // BQ, T // BK)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, dropout_rate=dropout_rate)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, qi, kj: (0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, qi, kj: (bh, kj, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BQ, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, 1, BQ), lambda bh, qi, kj: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((BQ,), jnp.float32),
            pltpu.VMEM((BQ,), jnp.float32),
            pltpu.VMEM((BQ, D), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3)
    return out.reshape(B, H, T, D), lse


def _flash_backward(q, k, v, o, lse, g, causal, sm_scale, dropout_rate, seed):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    q3, k3, v3 = (x.reshape(B * H, T, D) for x in (q, k, v))
    o3 = o.reshape(B * H, T, D)
    g3 = g.reshape(B * H, T, D)
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]

    from jax.experimental.pallas import tpu as pltpu

    BQ, BK = _blk(T, causal)
    dq_kernel = functools.partial(_flash_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, T // BQ, T // BK),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, qi, kj: (0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, 1, BQ), lambda bh, qi, kj: (bh, 0, qi)),
            pl.BlockSpec((1, 1, BQ), lambda bh, qi, kj: (bh, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, BQ, D), lambda bh, qi, kj: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3, g3, lse, delta)

    dkv_kernel = functools.partial(_flash_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, T // BK, T // BQ),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, kj, qi: (0, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, kj, qi: (bh, qi, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, BQ, D), lambda bh, kj, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, BQ), lambda bh, kj, qi: (bh, 0, qi)),
            pl.BlockSpec((1, 1, BQ), lambda bh, kj, qi: (bh, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda bh, kj, qi: (bh, kj, 0)),
            pl.BlockSpec((1, BK, D), lambda bh, kj, qi: (bh, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((BK, D), jnp.float32),
                        pltpu.VMEM((BK, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(_seed_arr(seed), q3, k3, v3, g3, lse, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _pallas_ok(q, dropout_rate=0.0):
    if jax.default_backend() == "cpu" and not _interpret():
        return False
    B, H, T, D = q.shape
    if _interpret() and dropout_rate:
        return False  # pltpu.prng_* has no interpreter implementation
    return T % 128 == 0 and D <= 256


# ---------------------------------------------------------------------------
# public entry: custom_vjp so program autodiff gets the Pallas backward
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def flash_attention(q, k, v, seed, causal=False, sm_scale=1.0,
                    dropout_rate=0.0):
    """seed: int32 scalar (traced) driving attention-weight dropout."""
    if _pallas_ok(q, dropout_rate):
        out, _ = _flash_forward(q, k, v, causal, sm_scale, dropout_rate, seed)
        return out
    return _attention_reference(q, k, v, causal, sm_scale, dropout_rate, seed)


def _fa_fwd(q, k, v, seed, causal, sm_scale, dropout_rate):
    if _pallas_ok(q, dropout_rate):
        out, lse = _flash_forward(q, k, v, causal, sm_scale, dropout_rate,
                                  seed)
        return out, (q, k, v, out, lse, seed)
    out = _attention_reference(q, k, v, causal, sm_scale, dropout_rate, seed)
    return out, (q, k, v, None, None, seed)


def _fa_bwd(causal, sm_scale, dropout_rate, res, g):
    q, k, v, o, lse, seed = res
    if o is not None:
        dq, dk, dv = _flash_backward(q, k, v, o, lse, g, causal, sm_scale,
                                     dropout_rate, seed)
    else:
        _, vjp = jax.vjp(
            lambda a, b, c: _attention_reference(a, b, c, causal, sm_scale,
                                                 dropout_rate, seed),
            q, k, v)
        dq, dk, dv = vjp(g)
    dseed = np.zeros(jnp.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dseed


flash_attention.defvjp(_fa_fwd, _fa_bwd)


@register_op("fused_attention", propagate_seqlen=False, needs_rng=True)
def _fused_attention(ctx, Q, K, V):
    """Q/K/V: [B, H, T, Dh]. attrs: causal, sm_scale, dropout_rate, is_test.

    Replaces the reference's matmul+softmax+dropout+matmul composition
    (nets.py:329) with one O(T)-memory kernel. Dropout is applied to the
    attention weights inside the kernel, keyed from the executor's
    functional PRNG."""
    sm_scale = ctx.attr("sm_scale", 1.0 / math.sqrt(Q.shape[-1]))
    causal = ctx.attr("causal", False)
    rate = 0.0 if ctx.attr("is_test", False) else ctx.attr("dropout_rate", 0.0)
    mesh = getattr(ctx.lowerer, "mesh", None) if ctx.lowerer else None
    if (mesh is not None and "sp" in mesh.axis_names
            and mesh.shape["sp"] > 1):
        # sequence parallelism: the ParallelExecutor shards the seq dim
        # over 'sp', so attention becomes Ring Attention — K/V shards
        # rotate over ICI while the online softmax accumulates.
        if rate:
            raise NotImplementedError(
                "attention-weight dropout is not supported under sequence "
                "parallelism; build the model with dropout_rate=0 (or move "
                "dropout outside the attention op)")
        if Q.shape[2] % mesh.shape["sp"] != 0:
            raise ValueError(
                f"sequence length {Q.shape[2]} is not divisible by the "
                f"{mesh.shape['sp']}-way 'sp' mesh axis; pad the sequence "
                f"or choose an sp that divides it")
        return {"Out": ring_attention(Q, K, V, mesh, axis="sp",
                                      causal=causal, sm_scale=sm_scale)}
    seed = jnp.uint32(0)
    if rate and ctx.key is not None:
        seed = jax.random.key_data(ctx.key).reshape(-1)[0]
    return {"Out": flash_attention(Q, K, V, seed.astype(jnp.int32), causal,
                                   sm_scale, float(rate))}


# ---------------------------------------------------------------------------
# ring attention: sequence parallelism over an 'sp' mesh axis
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, mesh, axis="sp", causal=False, sm_scale=None):
    """Exact attention with Q/K/V sequence-sharded over `axis`.

    Each device holds a [B, H, T/sp, D] shard; K/V shards rotate around the
    ring via ppermute while a running online-softmax (m, l, acc) accumulates
    — the Ring Attention algorithm. Communication rides ICI neighbor links;
    peak memory per chip is O(T/sp). Built from differentiable jax ops
    (ppermute has a transpose rule), so training works through it.

    Exceeds reference capability: the reference has no sequence parallelism
    (SURVEY.md §5.7).
    """
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map          # jax >= 0.8 home
        _replication_kw = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        _replication_kw = {"check_rep": False}

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    sp = mesh.shape[axis]

    def local(qs, ks, vs):
        idx = lax.axis_index(axis)
        Tl = qs.shape[2]

        def block(carry, chunk_i):
            m, l, acc, kc, vc = carry
            # which global chunk do we currently hold?
            src = (idx - chunk_i) % sp
            s = jnp.einsum("bhqd,bhkd->bhqk", qs, kc).astype(jnp.float32) \
                * sm_scale
            if causal:
                row = (idx * Tl + jnp.arange(Tl))[:, None]
                col = (src * Tl + jnp.arange(Tl))[None, :]
                s = jnp.where(col[None, None] > row[None, None], NEG_INF, s)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(vc.dtype), vc).astype(jnp.float32)
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (m_new, l_new, acc_new, kc, vc), None

        B, H, _, D = qs.shape
        m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, Tl), jnp.float32)
        acc0 = jnp.zeros((B, H, Tl, D), jnp.float32)
        (m, l, acc, _, _), _ = lax.scan(block, (m0, l0, acc0, ks, vs),
                                        jnp.arange(sp))
        return (acc / jnp.maximum(l, 1e-20)[..., None]).astype(qs.dtype)

    # carry the mesh's OTHER axes in the specs too: naming only 'sp' would
    # make GSPMD all-gather the full batch/head dims into every dp/mp
    # group and compute attention redundantly across them
    names = mesh.axis_names
    b_ax = "dp" if ("dp" in names and q.shape[0] % mesh.shape["dp"] == 0) \
        else None
    h_ax = "mp" if ("mp" in names and q.shape[1] % mesh.shape["mp"] == 0) \
        else None
    spec = P(b_ax, h_ax, axis, None)
    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, **_replication_kw)(q, k, v)
