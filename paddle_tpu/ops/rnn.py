"""Recurrent op lowerings: LSTM / GRU over padded variable-length batches.

Capability parity with the reference's fused recurrent kernels (reference:
paddle/fluid/operators/lstm_op.cc, gru_op.cc and their
math/lstm_compute,gru_compute CUDA backends; LoD shrinking machinery in
shrink_rnn_memory_op.cc / lod_rank_table).

TPU-native redesign: sequences are padded dense [B, T, ...] plus a `@SEQLEN`
length vector; the time loop is a `lax.scan` whose carry is masked per row, so
finished (padded) steps keep their state — the functional equivalent of the
reference's batch-shrinking dynamic RNN, but with static shapes XLA can tile
onto the MXU. Gate order is (i, f, g, o), documented — weights are learned so
layout differences from the reference do not affect capability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _reverse_padded(x, seqlen):
    """Per-row time reversal of a padded [B, T, ...] batch: valid prefix is
    reversed, padding stays in place."""
    B, T = x.shape[0], x.shape[1]
    t = jnp.arange(T)[None, :]
    L = seqlen.reshape(-1, 1)
    idx = jnp.where(t < L, L - 1 - t, t)
    return jnp.take_along_axis(x, idx.reshape(B, T, *([1] * (x.ndim - 2))), axis=1) \
        if x.ndim > 2 else jnp.take_along_axis(x, idx, axis=1)


@register_op("lstm", propagate_seqlen=True)
def _lstm(ctx, Input, Weight, Bias=None, H0=None, C0=None, SeqLen=None):
    """Input: [B, T, 4H] (x-projections), Weight: [H, 4H] recurrent,
    Bias: [1, 4H]. Outputs Hidden/Cell: [B, T, H]."""
    gate_act = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    cell_act = _ACTS[ctx.attr("cell_activation", "tanh")]
    cand_act = _ACTS[ctx.attr("candidate_activation", "tanh")]
    use_peep = ctx.attr("use_peepholes", False)
    B, T, H4 = Input.shape
    H = H4 // 4
    x = Input
    seqlen = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    if ctx.attr("is_reverse", False):
        x = _reverse_padded(x, seqlen)
    # peephole layout (reference lstm_op.cc): Bias [1, 7H] packs the 4H
    # gate biases then the diagonal peephole weights W_ic, W_if, W_oc —
    # elementwise cell taps on the i/f gates (c_prev) and o gate (c_new)
    w_ic = w_if = w_oc = None
    if use_peep and Bias is None:
        raise ValueError(
            "use_peepholes=True needs the fused [1,7H] bias tensor (it "
            "carries W_ic/W_if/W_oc); pass a bias or use_peepholes=False")
    if Bias is not None:
        b = Bias.reshape(-1)
        x = x + b[: 4 * H].reshape(1, 1, 4 * H)
        if use_peep:
            w_ic = b[4 * H:5 * H]
            w_if = b[5 * H:6 * H]
            w_oc = b[6 * H:7 * H]
    h0 = H0 if H0 is not None else jnp.zeros((B, H), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((B, H), Input.dtype)
    mask = (jnp.arange(T)[None, :] < seqlen.reshape(-1, 1)).astype(Input.dtype)  # [B,T]

    xt_seq = jnp.swapaxes(x, 0, 1)          # [T, B, 4H]
    m_seq = jnp.swapaxes(mask, 0, 1)[..., None]  # [T, B, 1]

    def step(carry, inp):
        h, c = carry
        xt, m = inp
        gates = xt + h @ Weight
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            i = i + w_ic * c
            f = f + w_if * c
        i, f = gate_act(i), gate_act(f)
        g = cand_act(g)
        c_new = f * c + i * g
        if w_oc is not None:
            o = o + w_oc * c_new
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        c_keep = m * c_new + (1.0 - m) * c
        h_keep = m * h_new + (1.0 - m) * h
        return (h_keep, c_keep), (h_new * m, c_new * m)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xt_seq, m_seq))
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if ctx.attr("is_reverse", False):
        hidden = _reverse_padded(hidden, seqlen)
        cell = _reverse_padded(cell, seqlen)
    return {"Hidden": hidden, "Cell": cell}


@register_op("gru", propagate_seqlen=True)
def _gru(ctx, Input, Weight, Bias=None, H0=None, SeqLen=None):
    """Input: [B, T, 3H] x-projections; Weight: [H, 3H] packed as
    [W_u | W_r | W_c]. Gate order (u, r, c)."""
    gate_act = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    cand_act = _ACTS[ctx.attr("activation", "tanh")]
    B, T, H3 = Input.shape
    H = H3 // 3
    x = Input
    seqlen = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    if ctx.attr("is_reverse", False):
        x = _reverse_padded(x, seqlen)
    if Bias is not None:
        x = x + Bias.reshape(1, 1, H3)
    h0 = H0 if H0 is not None else jnp.zeros((B, H), Input.dtype)
    mask = (jnp.arange(T)[None, :] < seqlen.reshape(-1, 1)).astype(Input.dtype)
    W_ur, W_c = Weight[:, : 2 * H], Weight[:, 2 * H:]

    xt_seq = jnp.swapaxes(x, 0, 1)
    m_seq = jnp.swapaxes(mask, 0, 1)[..., None]

    def step(h, inp):
        xt, m = inp
        ur = gate_act(xt[:, : 2 * H] + h @ W_ur)
        u, r = jnp.split(ur, 2, axis=-1)
        c = cand_act(xt[:, 2 * H:] + (r * h) @ W_c)
        h_new = (1.0 - u) * h + u * c
        h_keep = m * h_new + (1.0 - m) * h
        return h_keep, h_new * m

    _, hs = lax.scan(step, h0, (xt_seq, m_seq))
    hidden = jnp.swapaxes(hs, 0, 1)
    if ctx.attr("is_reverse", False):
        hidden = _reverse_padded(hidden, seqlen)
    return {"Hidden": hidden}


@register_op("lstm_unit", propagate_seqlen=False)
def _lstm_unit(ctx, X, C_prev):
    """One LSTM cell step on pre-projected gates X=[B,4H]
    (reference lstm_unit_op.cc)."""
    forget_bias = ctx.attr("forget_bias", 0.0)
    i, f, g, o = jnp.split(X, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * C_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit", propagate_seqlen=False)
def _gru_unit(ctx, Input, HiddenPrev, Weight, Bias=None):
    """One GRU step (reference gru_unit_op.cc). Input [B,3H] x-projection."""
    gate_act = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    cand_act = _ACTS[ctx.attr("activation", "tanh")]
    B, H3 = Input.shape
    H = H3 // 3
    x = Input if Bias is None else Input + Bias.reshape(1, H3)
    W_ur, W_c = Weight[:, : 2 * H], Weight[:, 2 * H:]
    ur = gate_act(x[:, : 2 * H] + HiddenPrev @ W_ur)
    u, r = jnp.split(ur, 2, axis=-1)
    c = cand_act(x[:, 2 * H:] + (r * HiddenPrev) @ W_c)
    h = (1.0 - u) * HiddenPrev + u * c
    return {"Hidden": h, "ResetHiddenPrev": r * HiddenPrev, "Gate": jnp.concatenate([u, r, c], -1)}


@register_op("lstmp", propagate_seqlen=True)
def _lstmp(ctx, Input, Weight, ProjWeight, Bias=None, H0=None, C0=None,
           SeqLen=None):
    """LSTM with recurrent projection (reference lstmp_op.cc): the gate
    recurrence consumes the PROJECTED state r = proj_act(h @ ProjWeight),
    shrinking the recurrent matmul from [H,4H] to [P,4H]. Input: [B,T,4H]
    x-projections; Weight: [P, 4H]; ProjWeight: [H, P]."""
    gate_act = _ACTS[ctx.attr("gate_activation", "sigmoid")]
    cell_act = _ACTS[ctx.attr("cell_activation", "tanh")]
    cand_act = _ACTS[ctx.attr("candidate_activation", "tanh")]
    proj_act = _ACTS[ctx.attr("proj_activation", "tanh")]
    use_peep = ctx.attr("use_peepholes", False)
    B, T, H4 = Input.shape
    H = H4 // 4
    P = ProjWeight.shape[1]
    x = Input
    seqlen = SeqLen if SeqLen is not None else jnp.full((B,), T, jnp.int32)
    if ctx.attr("is_reverse", False):
        x = _reverse_padded(x, seqlen)
    w_ic = w_if = w_oc = None
    if use_peep and Bias is None:
        raise ValueError(
            "use_peepholes=True needs the fused [1,7H] bias tensor (it "
            "carries W_ic/W_if/W_oc); pass a bias or use_peepholes=False")
    if Bias is not None:
        b = Bias.reshape(-1)
        x = x + b[: 4 * H].reshape(1, 1, 4 * H)
        if use_peep:       # [1,7H] layout, see _lstm
            w_ic = b[4 * H:5 * H]
            w_if = b[5 * H:6 * H]
            w_oc = b[6 * H:7 * H]
    r0 = H0 if H0 is not None else jnp.zeros((B, P), Input.dtype)
    c0 = C0 if C0 is not None else jnp.zeros((B, H), Input.dtype)
    mask = (jnp.arange(T)[None, :] < seqlen.reshape(-1, 1)).astype(Input.dtype)

    xt_seq = jnp.swapaxes(x, 0, 1)
    m_seq = jnp.swapaxes(mask, 0, 1)[..., None]

    def step(carry, inp):
        r, c = carry
        xt, m = inp
        gates = xt + r @ Weight
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            i = i + w_ic * c
            f = f + w_if * c
        i, f = gate_act(i), gate_act(f)
        c_new = f * c + i * cand_act(g)
        if w_oc is not None:
            o = o + w_oc * c_new
        o = gate_act(o)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ ProjWeight)
        c_keep = m * c_new + (1.0 - m) * c
        r_keep = m * r_new + (1.0 - m) * r
        return (r_keep, c_keep), (r_new * m, c_new * m)

    (_, _), (rs, cs) = lax.scan(step, (r0, c0), (xt_seq, m_seq))
    proj = jnp.swapaxes(rs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    if ctx.attr("is_reverse", False):
        proj = _reverse_padded(proj, seqlen)
        cell = _reverse_padded(cell, seqlen)
    return {"Projection": proj, "Cell": cell}
