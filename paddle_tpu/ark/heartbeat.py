"""Trainer-side heartbeat: renew a liveness lease on every pserver.

The server half lives in `pserver/server.py` (`_h_heartbeat` + the
`LeaseTable`-backed `EvictingBarrier`); this is the client half — a
daemon thread that renews the lease at `lease_s / 3` so two consecutive
losses still leave slack before expiry (the classic lease-renewal rule).
Heartbeats ride the normal RPC path with a SHORT deadline: a wedged
pserver must not wedge the heartbeat loop, and a missed beat is counted,
not raised — liveness signaling is best-effort by design.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

logger = logging.getLogger(__name__)


class HeartbeatThread:
    """Renews `trainer_id`'s lease on every endpoint until `stop()`.

    `lease_s` is the server-side lease duration; the renewal interval
    defaults to a third of it. Failures are swallowed (and metered when
    `observe` is on): the lease simply expires if the server is gone,
    which is exactly the signal the eviction path wants.

    fluid-fleet reuse: pass `beat=<callable>` instead of a
    client/endpoints pair to renew an arbitrary lease (a serving replica
    renewing its membership lease on the router) on the same
    interval/failure-swallowing contract — the callable does one renewal
    and raises on failure."""

    def __init__(self, client=None, endpoints: Sequence[str] = (),
                 trainer_id: int = 0, session=None, lease_s: float = 3.0,
                 interval: Optional[float] = None, beat=None,
                 quorum=None, quorum_resource: Optional[str] = None,
                 quorum_holder=None):
        if beat is None and client is None:
            raise ValueError("HeartbeatThread needs a client+endpoints "
                             "pair or a beat callable")
        self.client = client
        self.endpoints = list(endpoints)
        self.trainer_id = int(trainer_id)
        self.session = session
        self.lease_s = float(lease_s)
        self.interval = float(interval) if interval else self.lease_s / 3.0
        self._beat = beat
        # fluid-quorum opt-in: each renewal round ALSO asserts this
        # member's own lease at the arbiter group (resource/holder
        # default to the QuorumLeaseTable convention `member:<id>` /
        # `str(id)`; fleet replicas pass their own), so a lease table
        # with quorum backing can tell "member died" from "my link to
        # the member died". Best-effort like every beat — and ordered
        # AFTER the member beats with a failure backoff, so a degraded
        # quorum can never starve the real renewals past the lease.
        self.quorum = quorum
        self.quorum_resource = quorum_resource or f"member:{trainer_id}"
        self.quorum_holder = (str(quorum_holder)
                              if quorum_holder is not None
                              else str(trainer_id))
        self._quorum_lease = None
        self._quorum_retry_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatThread":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"heartbeat[trainer={self.trainer_id}]")
        self._thread.start()
        return self

    def beat_once(self) -> int:
        """One renewal round, all endpoints CONCURRENTLY (the client's
        per-endpoint pool); returns how many acknowledged. With a
        custom `beat` callable, one invocation (miss swallowed + metered
        under endpoint="custom", same contract). Concurrency
        matters: renewed serially, one blackholed pserver's deadline
        would delay renewals to the healthy ones past the lease and get
        this live trainer falsely evicted. Used synchronously at startup
        so the lease exists before the first sync barrier."""
        if self._beat is not None:
            try:
                self._beat()
                return 1
            except Exception as e:
                from .. import flags as _flags
                from ..observe import metrics as _metrics
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "ark_heartbeat_misses_total",
                        "heartbeat renewals that failed").inc(
                            endpoint="custom")
                logger.debug("custom heartbeat failed: %s", e)
                return 0
            finally:
                # quorum lease AFTER the member beat: a degraded
                # arbiter group (blackholed nodes eating their full
                # deadlines) must not delay the renewal the lease-table
                # owner is actually waiting for
                self._quorum_beat()
        futs = {ep: self.client._pool.submit(
                    self.client.heartbeat, ep, trainer_id=self.trainer_id,
                    session=self.session, lease_s=self.lease_s)
                for ep in self.endpoints}
        ok = 0
        for ep, f in futs.items():
            try:
                f.result()
                ok += 1
            except Exception as e:
                from .. import flags as _flags
                from ..observe import flight as _flight
                from ..observe import metrics as _metrics
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "ark_heartbeat_misses_total",
                        "heartbeat renewals that failed").inc(endpoint=ep)
                    _flight.note("heartbeat_miss", endpoint=ep,
                                 trainer_id=self.trainer_id,
                                 error=type(e).__name__)
                logger.debug("heartbeat to %s failed: %s", ep, e)
        self._quorum_beat()   # after the member beats (see __init__)
        return ok

    def _quorum_beat(self) -> None:
        """Renew (or first campaign for) this member's own quorum
        lease. `quorum_holder` identifies the member, so a
        `QuorumLeaseTable` can verify identity; a failed round is
        swallowed, metered, and BACKED OFF for a lease period — on the
        minority side of a partition, renew+campaign rounds wait out
        blackholed arbiters' deadlines, and repeating that every beat
        would stall the loop's real renewals (the false eviction this
        mechanism exists to prevent). The lease simply expires at the
        arbiters in the meantime, which is the honest signal."""
        import time as _time

        if self.quorum is None or _time.monotonic() < self._quorum_retry_at:
            return
        try:
            lease = self._quorum_lease
            if lease is not None and self.quorum.renew(lease):
                return
            self._quorum_lease = self.quorum.campaign(
                self.quorum_resource, self.quorum_holder, self.lease_s,
                max_rounds=1)
            if self._quorum_lease is None:
                self._quorum_retry_at = _time.monotonic() + self.lease_s
        except Exception as e:   # noqa: BLE001 — best-effort by contract
            from .. import flags as _flags
            from ..observe import metrics as _metrics
            self._quorum_retry_at = _time.monotonic() + self.lease_s
            if _flags.get_flag("observe"):
                _metrics.counter(
                    "ark_heartbeat_misses_total",
                    "heartbeat renewals that failed").inc(
                        endpoint="quorum")
            logger.debug("quorum member lease renewal failed: %s", e)

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat_once()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
