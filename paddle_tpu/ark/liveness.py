"""Trainer liveness: heartbeat leases + an evicting sync barrier.

Capability parity rationale: the reference's sync pserver
(listen_and_serv_op.cc RunSyncLoop) wedges the batch barrier until every
registered trainer arrives — a dead trainer stalls the world until an RPC
deadline fires. TensorFlow (Abadi et al., 2016) and every production PS
design solve this with leases: trainers renew a heartbeat lease, and the
barrier counts only live leaseholders, degrading gracefully to N-1
trainers when one dies instead of blocking on `sync_timeout`.

`LeaseTable` is the server-side liveness record; `EvictingBarrier`
replaces `threading.Barrier` for the sync-apply path — same
`wait/broken/reset` surface (it raises `threading.BrokenBarrierError` so
existing recovery code is unchanged) plus `evict`/`readmit`, with party
membership re-checked while waiting via an `evict_check` callback.
Trainers that never heartbeat hold no lease and are never evicted: the
legacy full-party/sync-timeout behavior is preserved for them.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

BrokenBarrierError = threading.BrokenBarrierError


class LeaseTable:
    """Per-member heartbeat leases: `beat` renews, `expired` lists
    leaseholders past their expiry. A member is only ever evictable
    after it has held a lease — unknown members are not tracked.

    Member ids are integers on the trainer path (trainer_id over the
    pserver RPC) and strings on the fluid-fleet path (replica ids like
    ``"r0@127.0.0.1:4471"`` heartbeating the serving router); `_key`
    keeps the legacy int coercion for numeric ids (np.int64 over the
    wire) while passing strings through untouched."""

    @staticmethod
    def _key(member):
        return member if isinstance(member, str) else int(member)

    def __init__(self):
        self._lock = threading.Lock()
        # member id -> (session, expires_at_monotonic, lease_s)
        self._leases: Dict[object, Tuple[object, float, float]] = {}

    def beat(self, trainer_id, session=None,
             lease_s: float = 3.0) -> None:
        with self._lock:
            self._leases[self._key(trainer_id)] = (
                session, time.monotonic() + float(lease_s), float(lease_s))

    def session_of(self, trainer_id):
        with self._lock:
            rec = self._leases.get(self._key(trainer_id))
            return rec[0] if rec else None

    def live(self) -> Iterable:
        now = time.monotonic()
        with self._lock:
            return [t for t, (_s, exp, _l) in self._leases.items()
                    if exp > now]

    def expired(self) -> Iterable:
        now = time.monotonic()
        with self._lock:
            return [t for t, (_s, exp, _l) in self._leases.items()
                    if exp <= now]

    def forget(self, trainer_id) -> None:
        with self._lock:
            self._leases.pop(self._key(trainer_id), None)

    def snapshot(self) -> Dict[int, Dict]:
        now = time.monotonic()
        with self._lock:
            return {t: {"session": s, "lease_s": l,
                        "expires_in_s": round(exp - now, 3),
                        "live": exp > now}
                    for t, (s, exp, l) in self._leases.items()}


class QuorumLeaseTable(LeaseTable):
    """fluid-quorum opt-in backing for membership leases: before a
    member whose LOCAL lease lapsed is reported expired, the arbiter
    group gets a second opinion. A member that lost its path to the
    table's owner (an asymmetric partition: replica <-> router cut,
    trainer <-> pserver cut) but still renews its own quorum lease at
    the arbiters is ALIVE — evicting it would shrink the world for a
    link failure, the exact false positive the crash-stop model could
    not exclude.

    Members renew their quorum lease themselves (`HeartbeatThread`'s
    `quorum=` option — resource `<prefix><member id>`, holder = the
    member id). Arbiter answers are cached for `status_ttl_s` so the
    eviction poll loop (~10 Hz while a barrier waits) does not hammer
    the group. Without a quorum client this IS a plain `LeaseTable`,
    bit for bit."""

    def __init__(self, quorum=None, resource_prefix: str = "member:",
                 status_ttl_s: float = 1.0):
        super().__init__()
        self.quorum = quorum
        self.resource_prefix = str(resource_prefix)
        self.status_ttl_s = float(status_ttl_s)
        self._q_cache: Dict[object, Tuple[float, bool]] = {}
        self._q_inflight: set = set()

    def _quorum_probe(self, key) -> bool:
        try:
            rec = self.quorum.holder(f"{self.resource_prefix}{key}")
            live = bool(rec and str(rec.get("holder")) == str(key))
        except Exception:   # noqa: BLE001 — unreachable arbiters add no
            live = False    # liveness evidence; the local verdict stands
        with self._lock:
            self._q_cache[key] = (time.monotonic(), live)
            self._q_inflight.discard(key)
            while len(self._q_cache) > 4096:
                self._q_cache.pop(next(iter(self._q_cache)))
        return live

    def _quorum_live(self, member, blocking: bool = True) -> bool:
        """The arbiters' opinion of `member`, cached `status_ttl_s`.
        `blocking=False` (the router's per-request dispatch path) never
        waits on an arbiter fan-out: a stale cached verdict is served
        while ONE background probe per member refreshes it, and an
        unknown member reads False (plain-table behavior) until the
        first probe lands — the holder() deadline must not become a
        recurring p99 spike on the serving hot path. Eviction decisions
        (`expired()`, a poll-loop context) stay blocking."""
        if self.quorum is None:
            return False
        key = self._key(member)
        now = time.monotonic()
        with self._lock:
            hit = self._q_cache.get(key)
            if hit is not None and now - hit[0] < self.status_ttl_s:
                return hit[1]
            if not blocking:
                stale = hit[1] if hit is not None else False
                if key not in self._q_inflight:
                    self._q_inflight.add(key)
                    threading.Thread(
                        target=self._quorum_probe, args=(key,),
                        daemon=True,
                        name=f"quorum-probe:{key}").start()
                return stale
        return self._quorum_probe(key)

    def expired(self) -> Iterable:
        return [t for t in super().expired() if not self._quorum_live(t)]

    def live(self) -> Iterable:
        """Locally-live members PLUS locally-expired ones the arbiters
        still vouch for (the fleet router's membership view: a replica
        the router cannot hear from directly stays a member; whether it
        can take traffic is the readiness poll's separate verdict).
        Non-blocking by design — this sits on the router's dispatch
        path (see `_quorum_live`)."""
        out = list(super().live())
        if self.quorum is not None:
            out += [t for t in super().expired()
                    if self._quorum_live(t, blocking=False)]
        return out

    def snapshot(self) -> Dict[int, Dict]:
        snap = super().snapshot()
        if self.quorum is not None:
            for t, rec in snap.items():
                if not rec["live"]:
                    rec["quorum_live"] = self._quorum_live(t)
        return snap


class EvictingBarrier:
    """A cyclic barrier over `parties` members whose effective party
    count shrinks when members are evicted (and grows back on readmit).

    `wait(timeout, evict_check, poll)` blocks until `arrived >= parties -
    evicted`; while blocked it invokes `evict_check()` every `poll`
    seconds so the owner can expire leases — an eviction that satisfies
    the barrier releases the waiters immediately rather than after
    `timeout`. The completing waiter runs `action` exactly once per
    generation before any waiter is released (threading.Barrier's action
    contract). On timeout the barrier breaks for the current generation:
    all of its waiters raise `threading.BrokenBarrierError` and new
    arrivals are refused until `reset()`."""

    def __init__(self, parties: int, action: Optional[Callable] = None):
        # RLock so evict_check callbacks may call evict()/live_parties
        # re-entrantly from inside wait()
        self._cond = threading.Condition(threading.RLock())
        self._full = int(parties)
        self._action = action
        self._evicted: set = set()
        # fluid-elastic scale-UP: members admitted via join() while a
        # generation is in flight wait here until the generation
        # boundary — the world never grows mid-batch; _joined remembers
        # landed admissions so a replayed join can never double-grow
        self._joining: set = set()
        self._joined: set = set()
        self._arrived = 0
        # members that identified themselves on arrival this generation:
        # evicting one of them must DISCOUNT its arrival, or the barrier
        # would release before the remaining live parties all arrive
        self._arrived_members: list = []
        self._gen = 0
        self._broken = False
        self._gen_status: Dict[int, str] = {}  # gen -> "done" | "broken"

    @property
    def parties(self) -> int:
        return self._full

    @property
    def live_parties(self) -> int:
        with self._cond:
            return self._full - len(self._evicted)

    @property
    def evicted(self) -> frozenset:
        with self._cond:
            return frozenset(self._evicted)

    @property
    def broken(self) -> bool:
        with self._cond:
            return self._broken

    def join(self, member) -> bool:
        """Grow the sync world by a NEW member (fluid-elastic scale-UP):
        admission lands at the NEXT generation boundary, never
        mid-batch — an in-flight generation's threshold is unchanged,
        and the joiner's arrival starts counting only once every member
        of the grown world can arrive too. An idle barrier (no arrivals
        this generation) admits immediately. Joining a member that was
        EVICTED is a readmit (the party count it once held grows back).
        Returns True when membership changed."""
        with self._cond:
            if member in self._evicted:
                self._evicted.discard(member)
                self._cond.notify_all()
                return True
            if member in self._joining or member in self._joined:
                return False               # replayed join: no double-grow
            if self._arrived == 0:
                self._full += 1
                self._joined.add(member)
                self._cond.notify_all()
            else:
                self._joining.add(member)
            return True

    def evict(self, member) -> bool:
        """Shrink the live party count by `member`; returns True when the
        eviction is new. If the member already ARRIVED this generation
        (identified wait), its arrival is discounted too — the shrunken
        threshold must be met by live arrivals only. Waiters re-check
        completion immediately."""
        with self._cond:
            if member in self._joining:
                # admitted-then-died before any generation boundary:
                # land the admission and evict it in ONE move (+1 full,
                # +1 evicted — the live count never moved), so a later
                # heartbeat READMITS it like any evicted member instead
                # of leaving it stranded outside every membership set
                # (where its arrivals would count as ghosts against a
                # threshold that never included it)
                self._joining.discard(member)
                self._full += 1
                self._joined.add(member)
                self._evicted.add(member)
                self._cond.notify_all()
                return True
            if member in self._evicted:
                return False
            if len(self._evicted) + 1 >= self._full:
                # never evict the last live party: an all-dead barrier is
                # a broken barrier, not a 0-party no-op
                return False
            self._evicted.add(member)
            if member in self._arrived_members:
                self._arrived_members.remove(member)
                self._arrived -= 1
            self._cond.notify_all()
            return True

    def readmit(self, member) -> bool:
        with self._cond:
            if member not in self._evicted:
                return False
            self._evicted.discard(member)
            return True

    def reset(self) -> None:
        """Clear a broken state; evictions persist (the dead stay dead
        until they heartbeat back in via `readmit`)."""
        with self._cond:
            self._broken = False
            self._arrived = 0
            self._arrived_members.clear()
            self._cond.notify_all()

    def _finish(self, gen: int, status: str) -> None:
        # caller holds the lock
        self._gen_status[gen] = status
        while len(self._gen_status) > 64:   # bound: waiters are short-lived
            self._gen_status.pop(next(iter(self._gen_status)))
        self._gen += 1
        self._arrived = 0
        self._arrived_members.clear()
        if self._joining:
            # the generation boundary: deferred admissions land now
            self._full += len(self._joining)
            self._joined |= self._joining
            self._joining.clear()
        if status == "broken":
            self._broken = True
        self._cond.notify_all()

    def wait(self, timeout: Optional[float] = None,
             evict_check: Optional[Callable] = None,
             poll: float = 0.1, member=None) -> int:
        """`member`, when given, identifies this arrival so `evict` can
        discount it; anonymous arrivals always count toward the
        threshold (legacy behavior)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            if self._broken:
                raise BrokenBarrierError
            gen = self._gen
            if member is not None and (member in self._evicted
                                       or member in self._joining):
                # a zombie arrival (evicted member not yet readmitted)
                # or a joiner awaiting its admission boundary must not
                # count toward the live threshold; it just waits out
                # the generation
                pass
            else:
                self._arrived += 1
                if member is not None:
                    self._arrived_members.append(member)
            while True:
                if evict_check is not None:
                    evict_check()   # may call self.evict() (RLock)
                status = self._gen_status.get(gen)
                if status == "done":
                    return gen
                if status == "broken":
                    raise BrokenBarrierError
                if self._gen == gen and \
                        self._arrived >= self._full - len(self._evicted):
                    try:
                        if self._action is not None:
                            self._action()
                    except BaseException:
                        self._finish(gen, "broken")
                        raise
                    self._finish(gen, "done")
                    return gen
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    self._finish(gen, "broken")
                    raise BrokenBarrierError
                slice_ = poll if evict_check is not None else remaining
                if remaining is not None:
                    slice_ = remaining if slice_ is None \
                        else min(slice_, remaining)
                self._cond.wait(slice_)
