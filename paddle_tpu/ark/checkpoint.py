"""Durable, atomic, verifiable checkpoints.

Capability parity with the reference checkpoint protocol
(reference: python/paddle/fluid/trainer.py:98 `CheckpointConfig`,
`save_checkpoint` :637 / `load_checkpoint` :737, `_scroll_delete` :1164
rotation, `_write_success` :1186; the distribute transpiler's
checkpoint-notify so pservers save their shards alongside the trainer),
hardened for crash safety:

- **Atomic commit**: a checkpoint is staged in a hidden tmp dir on the
  same filesystem and committed with ONE `os.replace` — a crash at any
  point mid-save leaves either the previous serials intact and the stage
  invisible, or the new serial fully present. There is no `_SUCCESS`
  marker race: the committed dir name IS the success marker.
- **MANIFEST**: every committed serial carries a `MANIFEST.json` with the
  format version, the training cursor (epoch/step/step-in-epoch), RNG
  stream state (the executor run counters that derive the per-step PRNG
  keys), and a sha256 per payload file — `load_checkpoint(verify=True)`
  refuses a bit-rotted or torn checkpoint instead of half-loading it.
- **Rotation**: retain the newest `max_num_checkpoints` serials; older
  ones (and any stale stage dirs from a crashed saver) are deleted after
  a successful commit, never before.
- **Sharded writers**: parameter servers join the same protocol — the
  stage dir is handed to a `shard_saver` callback (PSClient.save) before
  commit, each shard writes its npz atomically with a sidecar manifest,
  and the committing MANIFEST checksums every file it finds, so trainer
  state and all pserver shards commit as one consistent unit.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import tempfile
import time
import uuid
from typing import Callable, Dict, Optional, Tuple

import numpy as np

FORMAT_VERSION = 1
SERIAL_PREFIX = "ark_"
STAGE_PREFIX = ".stage_"
MANIFEST_NAME = "MANIFEST.json"
STATE_NAME = "state.npz"
SIDECAR_SUFFIX = ".manifest.json"


class CheckpointError(RuntimeError):
    """A checkpoint is missing, torn, or fails checksum verification."""


class CheckpointConfig:
    """Auto-checkpoint policy for `Trainer.train(..., checkpoint=cfg)`
    (reference trainer.py:98, with the ark durable format underneath).

    `step_interval` saves every N global steps; `epoch_interval` saves at
    the end of every N-th epoch; `verify_on_load` checks manifest sha256s
    before trusting a resume (cheap relative to a training run)."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3, epoch_interval: int = 1,
                 step_interval: int = 10, verify_on_load: bool = True):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoint")
        self.max_num_checkpoints = max(int(max_num_checkpoints), 1)
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.verify_on_load = verify_on_load


# -- atomic file primitives ---------------------------------------------

def fsync_dir(path: str) -> None:
    """Flush a DIRECTORY's metadata (the renames/unlinks inside it) to
    disk. Without this an `os.replace` is only process-crash safe: the
    new name lives in the page cache and a power loss can lose the
    rename while a later unlink persisted. Best-effort — some
    filesystems refuse dirfd fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_file(path: str, mode: str = "wb"):
    """Write `path` all-or-nothing: the data goes to a same-directory tmp
    file, is fsynced, and lands under the final name with one
    `os.replace` (then the directory entry is fsynced too). A crash
    mid-write leaves the previous contents (or absence) of `path`
    untouched — never a torn file."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp_",
                               suffix=os.path.basename(path))
    try:
        with os.fdopen(fd, mode) as f:
            yield f
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def write_sidecar_manifest(path: str, **extra) -> str:
    """Checksum sidecar for an independently-written shard file (the
    pserver `_h_save` protocol): `<path>.manifest.json` carries the
    sha256 + byte count so `recover()` and `verify_checkpoint()` can
    refuse a torn shard. Written atomically AFTER the payload, so a
    sidecar's presence implies the payload committed."""
    side = path + SIDECAR_SUFFIX
    meta = {"file": os.path.basename(path), "sha256": file_sha256(path),
            "bytes": os.path.getsize(path), **extra}
    with atomic_file(side, "w") as f:
        json.dump(meta, f, indent=1)
    return side


def verify_sidecar(path: str) -> None:
    """Raise CheckpointError if `path` disagrees with its sidecar (a
    missing sidecar passes — pre-ark shards have none)."""
    side = path + SIDECAR_SUFFIX
    if not os.path.exists(side):
        return
    with open(side) as f:
        meta = json.load(f)
    if not os.path.exists(path):
        raise CheckpointError(f"shard {path} is missing but its sidecar "
                              f"manifest exists")
    got = file_sha256(path)
    if got != meta["sha256"]:
        raise CheckpointError(
            f"shard {path} fails checksum verification: sha256 {got} != "
            f"manifest {meta['sha256']} — torn or corrupted shard")


# -- serial-dir layout ---------------------------------------------------

def _serial_dir(root: str, serial: int) -> str:
    return os.path.join(root, f"{SERIAL_PREFIX}{serial:08d}")


def list_checkpoints(checkpoint_dir: str):
    """[(serial, path)] of COMMITTED serials, ascending. Stage dirs and
    foreign entries are ignored — commit is the only success marker."""
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in os.listdir(checkpoint_dir):
        if not name.startswith(SERIAL_PREFIX):
            continue
        tail = name[len(SERIAL_PREFIX):]
        if not tail.isdigit():
            continue
        path = os.path.join(checkpoint_dir, name)
        if os.path.isfile(os.path.join(path, MANIFEST_NAME)):
            out.append((int(tail), path))
    out.sort()
    return out


def latest_checkpoint(checkpoint_dir: str,
                      verify: bool = False) -> Optional[str]:
    """Path of the newest committed serial, or None. With `verify=True`
    serials failing checksum verification are skipped (newest intact one
    wins) — the load-side half of crash safety."""
    for _, path in reversed(list_checkpoints(checkpoint_dir)):
        if verify:
            try:
                verify_checkpoint(path)
            except CheckpointError:
                continue
        return path
    return None


def read_manifest(ckpt_path: str) -> Dict:
    mpath = os.path.join(ckpt_path, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"{ckpt_path} has no {MANIFEST_NAME} — not a "
                              f"committed ark checkpoint")
    with open(mpath) as f:
        return json.load(f)


def verify_checkpoint(ckpt_path: str) -> Dict:
    """Check every file the MANIFEST names against its recorded sha256
    (and every pserver sidecar against its shard). Returns the manifest;
    raises CheckpointError naming the first mismatch."""
    manifest = read_manifest(ckpt_path)
    for fname, meta in manifest.get("files", {}).items():
        fpath = os.path.join(ckpt_path, fname)
        if not os.path.exists(fpath):
            raise CheckpointError(
                f"checkpoint {ckpt_path} is torn: {fname} named by "
                f"MANIFEST is missing")
        got = file_sha256(fpath)
        if got != meta["sha256"]:
            raise CheckpointError(
                f"checkpoint {ckpt_path} fails verification: {fname} "
                f"sha256 {got} != manifest {meta['sha256']}")
    return manifest


# -- save / load ---------------------------------------------------------

def save_checkpoint(checkpoint_dir: str,
                    arrays: Dict[str, np.ndarray],
                    cursor: Optional[Dict] = None,
                    rng: Optional[Dict] = None,
                    max_num_checkpoints: int = 3,
                    shard_saver: Optional[Callable[[str], object]] = None,
                    extra: Optional[Dict] = None) -> str:
    """Commit one new serial atomically; returns its path.

    `arrays` (var name -> ndarray) is the trainer-side state — parameters
    AND optimizer slot vars. `cursor` records where training stood
    ({"epoch_id", "step_id", "step_in_epoch"}); `rng` records the
    executor PRNG stream state ({"train_runs", "stream"}) so a resume
    reproduces the uninterrupted run's draws bit-for-bit. `shard_saver`,
    if given, is called with the STAGE path before commit — pservers
    write their shards into it (PSClient.save), joining the same atomic
    unit. Every file present at commit time is checksummed into the
    MANIFEST."""
    from ..observe import metrics as _metrics
    from .. import flags as _flags

    t0 = time.perf_counter()
    os.makedirs(checkpoint_dir, exist_ok=True)
    committed = list_checkpoints(checkpoint_dir)
    serial = committed[-1][0] + 1 if committed else 0
    stage = os.path.join(checkpoint_dir,
                         f"{STAGE_PREFIX}{serial:08d}_{uuid.uuid4().hex}")
    os.makedirs(stage)
    try:
        if arrays:
            with atomic_file(os.path.join(stage, STATE_NAME)) as f:
                np.savez(f, **arrays)
        if shard_saver is not None:
            shard_saver(stage)
        files = {}
        for root, _dirs, names in os.walk(stage):
            for name in names:
                if name == MANIFEST_NAME:
                    continue
                fpath = os.path.join(root, name)
                rel = os.path.relpath(fpath, stage)
                side = fpath + SIDECAR_SUFFIX
                if os.path.exists(side):
                    # the shard writer already hashed this payload into
                    # its sidecar — trust it rather than re-reading every
                    # shard byte (the sidecar itself is hashed below)
                    with open(side) as sf:
                        smeta = json.load(sf)
                    files[rel] = {"sha256": smeta["sha256"],
                                  "bytes": smeta["bytes"]}
                else:
                    files[rel] = {"sha256": file_sha256(fpath),
                                  "bytes": os.path.getsize(fpath)}
        manifest = {
            "format_version": FORMAT_VERSION,
            "serial": serial,
            "wall_time": time.time(),
            "cursor": dict(cursor or {}),
            "rng": dict(rng or {}),
            "files": files,
        }
        if extra:
            manifest.update(extra)
        with atomic_file(os.path.join(stage, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=1)
        final = _serial_dir(checkpoint_dir, serial)
        # the commit point: one rename. A concurrent saver losing the race
        # (final already exists) fails here and its stage is discarded.
        os.replace(stage, final)
        # make the commit DURABLE before rotation may unlink an older
        # serial: without the dir fsync a power loss could lose the
        # rename while the unlink persisted, leaving fewer intact
        # serials than promised (or none)
        fsync_dir(checkpoint_dir)
    except BaseException:
        shutil.rmtree(stage, ignore_errors=True)
        raise
    _rotate(checkpoint_dir, max_num_checkpoints)
    if _flags.get_flag("observe"):
        _metrics.counter("ark_checkpoints_saved_total",
                         "committed ark checkpoints").inc()
        _metrics.histogram("ark_checkpoint_save_seconds",
                           "wall time of save_checkpoint").observe(
                               time.perf_counter() - t0)
    return final


def _rotate(checkpoint_dir: str, keep: int) -> None:
    """Delete serials beyond the newest `keep`, plus DEAD stage dirs.
    Runs only after a successful commit. A stage is provably dead once
    its serial is <= the newest committed one (its commit rename would
    hit an existing target); stages for higher serials may belong to a
    concurrent live saver and are left alone."""
    committed = list_checkpoints(checkpoint_dir)
    newest = committed[-1][0] if committed else -1
    for _serial, path in committed[: max(0, len(committed) - keep)]:
        shutil.rmtree(path, ignore_errors=True)
    for name in os.listdir(checkpoint_dir):
        if not name.startswith(STAGE_PREFIX):
            continue
        serial_s = name[len(STAGE_PREFIX):].split("_", 1)[0]
        if serial_s.isdigit() and int(serial_s) > newest:
            continue
        shutil.rmtree(os.path.join(checkpoint_dir, name),
                      ignore_errors=True)


def load_checkpoint(ckpt_path: str,
                    verify: bool = True) -> Tuple[Dict[str, np.ndarray],
                                                  Dict]:
    """Read one committed serial -> (arrays, manifest). `verify=True`
    checksums every manifest-named file first and refuses a torn or
    corrupted checkpoint with CheckpointError (callers fall back to
    `latest_checkpoint(..., verify=True)` for the newest intact one)."""
    from ..observe import metrics as _metrics
    from .. import flags as _flags

    manifest = (verify_checkpoint(ckpt_path) if verify
                else read_manifest(ckpt_path))
    arrays: Dict[str, np.ndarray] = {}
    state = os.path.join(ckpt_path, STATE_NAME)
    if os.path.exists(state):
        with np.load(state, allow_pickle=False) as z:
            arrays = {k: z[k].copy() for k in z.files}
    if _flags.get_flag("observe"):
        _metrics.counter("ark_checkpoints_loaded_total",
                         "ark checkpoints restored").inc()
    return arrays, manifest
