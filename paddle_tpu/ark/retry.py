"""Bounded retry/backoff policy for host RPCs.

Capability parity with the reference gRPC client's retry knobs (reference:
paddle/fluid/operators/distributed/grpc_client.cc — `FLAGS_rpc_retry_times`
/ retry_time_ backoff in AsyncSendVar; TensorFlow's whitepaper makes the
same point: retried RPCs are half of user-visible fault tolerance, the
other half being checkpoints).

The policy is a small immutable config; `PSClient` consults it per call.
Backoff is bounded exponential with jitter: attempt k sleeps
`min(max_delay, base_delay * 2**k)` scaled by a uniform factor in
`[1 - jitter, 1 + jitter]`. A seeded policy draws its jitter from a
private `random.Random(seed)` so chaos tests replay identical schedules.
"""

from __future__ import annotations

import random
from typing import Optional


class RetryPolicy:
    """How many times to retry a failed RPC and how long to wait between
    attempts. `max_attempts` counts RETRIES (0 disables retrying); the
    original call is always made. `deadline` is the default per-call wall
    budget in seconds (None = no deadline: a call may block indefinitely,
    the pre-ark behavior)."""

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.5,
                 deadline: Optional[float] = None,
                 seed: Optional[int] = None):
        if max_attempts < 0:
            raise ValueError(f"max_attempts must be >= 0, got {max_attempts}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.deadline = deadline
        self._rng = random.Random(seed) if seed is not None else random

    def backoff(self, attempt: int) -> float:
        """Sleep before retry number `attempt` (0-based)."""
        d = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base_delay={self.base_delay}, max_delay={self.max_delay}, "
                f"jitter={self.jitter}, deadline={self.deadline})")


#: retrying disabled — the pre-ark fail-fast behavior, used by tests that
#: assert on first-failure semantics
NO_RETRY = RetryPolicy(max_attempts=0, deadline=None)
