"""Seed-deterministic fault injection for the pserver wire protocol.

The chaos harness proves the recovery paths actually recover: it wraps
`pserver.rpc.send_msg`/`recv_msg` through the module's fault hook and
injects the failure modes a flaky network or a dying process produces —

    drop      the request/reply vanishes (blackhole; the peer never sees
              it, the caller blocks until its deadline)
    delay     the message is late by a uniform draw from `delay_s`
    truncate  the connection dies MID-FRAME: a prefix of the wire bytes
              is delivered, then the socket closes (exercises the
              `RPCConnectionError` bytes-read/expected path)
    close     the connection dies cleanly before the message

plus process-level helpers: `kill_server` is a SIGKILL-equivalent hard
cut of an in-process `ParameterServer` (its `stop()` already drops
in-flight requests unanswered by contract), and `restart_server` brings
a fresh server up on the same endpoint, optionally recovering its shard
from a checkpoint.

Every decision comes from one `random.Random(seed)` stream, so a failing
chaos run replays byte-identically. Faults are injected on ONE side
(default the client's) selected by thread name — pserver connection
threads are named `psconn@<endpoint>` — so a drill can separately attack
requests and replies.
"""

from __future__ import annotations

import contextlib
import logging
import random
import threading
import time
from typing import Optional, Set, Tuple

logger = logging.getLogger(__name__)


def _rpc():
    # lazy: pserver.client imports ark.retry, so a module-level import
    # here would close an import cycle through the two packages
    from ..pserver import rpc
    return rpc


def _is_server_thread() -> bool:
    return threading.current_thread().name.startswith("psconn@")


# -- actor identity (fluid-quorum / NetPartition) -------------------------
#
# A partition is defined between ACTORS (logical processes), not
# sockets. The sender of a message is identified, in order: an explicit
# thread-local set via `acting_as(endpoint)` (how a pooled client
# thread inherits its owner's identity), else the `...@<endpoint>`
# suffix every server-owned thread already carries (psconn@/qconn@
# connection threads, haven-fwd@/haven-monitor@/quorum-renew@ loops).
# Threads with neither (a trainer's own threads) are the anonymous
# actor None, which partition rules can target with the "*" wildcard.

_thread_actor = threading.local()


def set_thread_actor(endpoint: Optional[str]) -> None:
    _thread_actor.endpoint = endpoint


@contextlib.contextmanager
def acting_as(endpoint: Optional[str]):
    """Attribute every message this thread sends inside the context to
    `endpoint` — how a client owned by server X marks its outbound
    traffic as X's even from a shared worker pool."""
    prev = getattr(_thread_actor, "endpoint", None)
    _thread_actor.endpoint = endpoint
    try:
        yield
    finally:
        _thread_actor.endpoint = prev


def current_actor() -> Optional[str]:
    ep = getattr(_thread_actor, "endpoint", None)
    if ep is not None:
        return ep
    name = threading.current_thread().name
    if "@" in name:
        return name.rsplit("@", 1)[1]
    return None


class ChaosMonkey:
    """Install with `with ChaosMonkey(seed=..., p_drop=0.1): ...` (or
    `.start()` / `.stop()`). Probabilities are per-message; `side`
    selects whose sends are attacked: "client" (requests), "server"
    (replies), or "both". Counters on the instance record what fired so
    tests can assert the fault actually happened."""

    def __init__(self, seed: int = 0, p_drop: float = 0.0,
                 p_delay: float = 0.0, p_truncate: float = 0.0,
                 p_close: float = 0.0,
                 delay_s: Tuple[float, float] = (0.005, 0.05),
                 side: str = "client"):
        if side not in ("client", "server", "both"):
            raise ValueError(f"side must be client/server/both, got {side!r}")
        self.rng = random.Random(seed)
        self.p_drop, self.p_delay = p_drop, p_delay
        self.p_truncate, self.p_close = p_truncate, p_close
        self.delay_s = delay_s
        self.side = side
        self.injected = {"drop": 0, "delay": 0, "truncate": 0, "close": 0}
        self._lock = threading.Lock()
        self._installed = False

    # -- hook ------------------------------------------------------------
    def _applies(self) -> bool:
        on_server = _is_server_thread()
        return (self.side == "both"
                or (self.side == "server") == on_server)

    def _hook(self, direction: str, sock, data: Optional[bytes]):
        """rpc fault hook: returns the (possibly modified) bytes to send,
        or None when the hook consumed/discarded the message itself.
        For `recv` only delay/close apply (data is None)."""
        if not self._applies():
            return data
        with self._lock:   # one deterministic decision stream
            r = self.rng.random()
            p = 0.0
            for fault in ("drop", "delay", "truncate", "close"):
                p += getattr(self, f"p_{fault}")
                if r < p:
                    break
            else:
                return data
            # drop/truncate/close are SEND faults: the connection (or
            # message) dies before the request leaves, which the caller
            # may safely replay. Attacking the other direction (replies
            # lost AFTER the server applied the request — the genuinely
            # ambiguous failure) is side="server": the server's reply IS
            # its send.
            if direction == "recv" and fault != "delay":
                return data
            self.injected[fault] += 1
            if fault == "delay":
                lo, hi = self.delay_s
                pause = lo + (hi - lo) * self.rng.random()
            elif fault == "truncate" and data is not None:
                cut = 1 + int(self.rng.random() * max(len(data) - 1, 1))
        if fault == "delay":
            time.sleep(pause)
            return data
        if fault == "close":
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"chaos: connection closed before {direction}")
        if fault == "drop":
            logger.debug("chaos: dropped a %d-byte message", len(data))
            return None   # blackhole: caller believes it sent
        # truncate: deliver a strict prefix, then kill the connection —
        # the peer's _recv_exact dies mid-frame with RPCConnectionError
        try:
            sock.sendall(data[:cut])
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        logger.debug("chaos: truncated %d-byte message at %d", len(data), cut)
        return None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ChaosMonkey":
        if self._installed:
            return self
        rpc = _rpc()
        if rpc.get_fault_hook() is not None:
            raise RuntimeError("another fault hook is already installed")
        rpc.set_fault_hook(self._hook)
        self._installed = True
        return self

    def stop(self) -> None:
        if self._installed:
            _rpc().set_fault_hook(None)
            self._installed = False

    def __enter__(self) -> "ChaosMonkey":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def total_injected(self) -> int:
        return sum(self.injected.values())


class NetPartition:
    """Pair-wise DIRECTIONAL network partition over the rpc fault hook
    (fluid-quorum). A rule `(src, dst)` blackholes every request the
    actor `src` initiates toward the listening endpoint `dst`: the wire
    bytes are consumed at send time, so the sender believes it sent and
    then waits out its own deadline — exactly what a partition looks
    like from inside a process. Because the cut is at request
    initiation, the reply path of a blocked request needs no separate
    rule (the request never arrived); reply-only loss — the genuinely
    ambiguous failure — stays `ChaosMonkey(side="server")`'s job.

    `src` is an actor name (see `current_actor()`: an explicit
    `acting_as` scope, else the thread's `...@<endpoint>` suffix); `"*"`
    matches any actor including the anonymous one. `dst` is the target's
    listening endpoint as the client dials it; `"*"` matches all.

    `p < 1.0` drops each matched message by an independent draw from one
    `random.Random(seed)` stream — a flaky (not severed) link, replayed
    byte-identically per seed. Default p=1.0 is a full cut.

        with NetPartition(seed=7) as net:
            net.isolate(primary_ep, backup_ep)       # both directions
            net.block(primary_ep, arbiter2_ep)       # one direction
            ...
            net.heal()                               # all traffic flows
    """

    def __init__(self, seed: int = 0, p: float = 1.0):
        self.rng = random.Random(seed)
        self.p = float(p)
        self._rules: Set[Tuple[str, str]] = set()
        self._lock = threading.Lock()
        self._installed = False
        self.dropped = 0

    # -- rules -----------------------------------------------------------
    def block(self, src: str, dst: str) -> "NetPartition":
        with self._lock:
            self._rules.add((src, dst))
        return self

    def isolate(self, a: str, b: str) -> "NetPartition":
        """Cut the pair in both request directions."""
        return self.block(a, b).block(b, a)

    def heal(self, src: Optional[str] = None,
             dst: Optional[str] = None) -> None:
        """Remove matching rules (no args: remove ALL — full heal)."""
        with self._lock:
            if src is None and dst is None:
                self._rules.clear()
            else:
                self._rules = {(s, d) for s, d in self._rules
                               if not ((src is None or s == src)
                                       and (dst is None or d == dst))}

    def blocks(self, src: Optional[str], dst: str) -> bool:
        with self._lock:
            for s, d in self._rules:
                if (s == "*" or s == src) and (d == "*" or d == dst):
                    return True
        return False

    # -- hook ------------------------------------------------------------
    def _hook(self, direction: str, sock, data: Optional[bytes]):
        if direction != "send" or data is None:
            return data
        try:
            host, port = sock.getpeername()[:2]
        except OSError:
            return data
        dst = f"{host}:{port}"
        if not self.blocks(current_actor(), dst):
            return data
        with self._lock:
            if self.p < 1.0 and self.rng.random() >= self.p:
                return data
            self.dropped += 1
        logger.debug("partition: dropped %d bytes %s -> %s", len(data),
                     current_actor(), dst)
        return None   # blackhole: the caller believes it sent

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "NetPartition":
        if self._installed:
            return self
        rpc = _rpc()
        if rpc.get_fault_hook() is not None:
            raise RuntimeError("another fault hook is already installed")
        rpc.set_fault_hook(self._hook)
        self._installed = True
        return self

    def stop(self) -> None:
        if self._installed:
            _rpc().set_fault_hook(None)
            self._installed = False

    def __enter__(self) -> "NetPartition":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- process-level faults -------------------------------------------------

def kill_server(server) -> str:
    """SIGKILL-equivalent death of an in-process ParameterServer: the
    listener closes and every in-flight request is dropped unanswered
    (`_serve_conn` checks the stop event before replying). Returns the
    endpoint so `restart_server` can reuse it."""
    from ..observe import flight as _flight

    ep = server.endpoint
    _flight.note("chaos_kill", endpoint=ep)
    server.stop()
    return ep


def kill_master(master) -> str:
    """SIGKILL-equivalent death of an in-process data `Master`
    (fluid-elastic): listener and every live connection die now,
    in-flight requests dropped unanswered, and its quorum lease is NOT
    resigned — it expires at the arbiters like a real dead process's
    would. Returns the endpoint."""
    from ..observe import flight as _flight

    ep = master.endpoint
    _flight.note("chaos_kill_master", endpoint=ep)
    master.stop()
    return ep


def restart_server(endpoint: str, trainers: int = 1,
                   sync_timeout: float = 120.0,
                   recover_dir: Optional[str] = None):
    """Bring a fresh ParameterServer up on `endpoint`, recovering its
    shard (values + optimizer slots + sparse tables) from `recover_dir`
    when given — the crash/restart leg of the drill."""
    from ..observe import flight as _flight
    from ..pserver.server import ParameterServer

    srv = ParameterServer(endpoint, trainers=trainers,
                          sync_timeout=sync_timeout).start()
    if recover_dir is not None:
        srv.recover(recover_dir)
    _flight.note("chaos_restart", endpoint=endpoint,
                 recovered=recover_dir is not None)
    return srv
