"""Seed-deterministic fault injection for the pserver wire protocol.

The chaos harness proves the recovery paths actually recover: it wraps
`pserver.rpc.send_msg`/`recv_msg` through the module's fault hook and
injects the failure modes a flaky network or a dying process produces —

    drop      the request/reply vanishes (blackhole; the peer never sees
              it, the caller blocks until its deadline)
    delay     the message is late by a uniform draw from `delay_s`
    truncate  the connection dies MID-FRAME: a prefix of the wire bytes
              is delivered, then the socket closes (exercises the
              `RPCConnectionError` bytes-read/expected path)
    close     the connection dies cleanly before the message

plus process-level helpers: `kill_server` is a SIGKILL-equivalent hard
cut of an in-process `ParameterServer` (its `stop()` already drops
in-flight requests unanswered by contract), and `restart_server` brings
a fresh server up on the same endpoint, optionally recovering its shard
from a checkpoint.

Every decision comes from one `random.Random(seed)` stream, so a failing
chaos run replays byte-identically. Faults are injected on ONE side
(default the client's) selected by thread name — pserver connection
threads are named `psconn@<endpoint>` — so a drill can separately attack
requests and replies.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional, Tuple

logger = logging.getLogger(__name__)


def _rpc():
    # lazy: pserver.client imports ark.retry, so a module-level import
    # here would close an import cycle through the two packages
    from ..pserver import rpc
    return rpc


def _is_server_thread() -> bool:
    return threading.current_thread().name.startswith("psconn@")


class ChaosMonkey:
    """Install with `with ChaosMonkey(seed=..., p_drop=0.1): ...` (or
    `.start()` / `.stop()`). Probabilities are per-message; `side`
    selects whose sends are attacked: "client" (requests), "server"
    (replies), or "both". Counters on the instance record what fired so
    tests can assert the fault actually happened."""

    def __init__(self, seed: int = 0, p_drop: float = 0.0,
                 p_delay: float = 0.0, p_truncate: float = 0.0,
                 p_close: float = 0.0,
                 delay_s: Tuple[float, float] = (0.005, 0.05),
                 side: str = "client"):
        if side not in ("client", "server", "both"):
            raise ValueError(f"side must be client/server/both, got {side!r}")
        self.rng = random.Random(seed)
        self.p_drop, self.p_delay = p_drop, p_delay
        self.p_truncate, self.p_close = p_truncate, p_close
        self.delay_s = delay_s
        self.side = side
        self.injected = {"drop": 0, "delay": 0, "truncate": 0, "close": 0}
        self._lock = threading.Lock()
        self._installed = False

    # -- hook ------------------------------------------------------------
    def _applies(self) -> bool:
        on_server = _is_server_thread()
        return (self.side == "both"
                or (self.side == "server") == on_server)

    def _hook(self, direction: str, sock, data: Optional[bytes]):
        """rpc fault hook: returns the (possibly modified) bytes to send,
        or None when the hook consumed/discarded the message itself.
        For `recv` only delay/close apply (data is None)."""
        if not self._applies():
            return data
        with self._lock:   # one deterministic decision stream
            r = self.rng.random()
            p = 0.0
            for fault in ("drop", "delay", "truncate", "close"):
                p += getattr(self, f"p_{fault}")
                if r < p:
                    break
            else:
                return data
            # drop/truncate/close are SEND faults: the connection (or
            # message) dies before the request leaves, which the caller
            # may safely replay. Attacking the other direction (replies
            # lost AFTER the server applied the request — the genuinely
            # ambiguous failure) is side="server": the server's reply IS
            # its send.
            if direction == "recv" and fault != "delay":
                return data
            self.injected[fault] += 1
            if fault == "delay":
                lo, hi = self.delay_s
                pause = lo + (hi - lo) * self.rng.random()
            elif fault == "truncate" and data is not None:
                cut = 1 + int(self.rng.random() * max(len(data) - 1, 1))
        if fault == "delay":
            time.sleep(pause)
            return data
        if fault == "close":
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"chaos: connection closed before {direction}")
        if fault == "drop":
            logger.debug("chaos: dropped a %d-byte message", len(data))
            return None   # blackhole: caller believes it sent
        # truncate: deliver a strict prefix, then kill the connection —
        # the peer's _recv_exact dies mid-frame with RPCConnectionError
        try:
            sock.sendall(data[:cut])
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        logger.debug("chaos: truncated %d-byte message at %d", len(data), cut)
        return None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ChaosMonkey":
        if self._installed:
            return self
        rpc = _rpc()
        if rpc.get_fault_hook() is not None:
            raise RuntimeError("another fault hook is already installed")
        rpc.set_fault_hook(self._hook)
        self._installed = True
        return self

    def stop(self) -> None:
        if self._installed:
            _rpc().set_fault_hook(None)
            self._installed = False

    def __enter__(self) -> "ChaosMonkey":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def total_injected(self) -> int:
        return sum(self.injected.values())


# -- process-level faults -------------------------------------------------

def kill_server(server) -> str:
    """SIGKILL-equivalent death of an in-process ParameterServer: the
    listener closes and every in-flight request is dropped unanswered
    (`_serve_conn` checks the stop event before replying). Returns the
    endpoint so `restart_server` can reuse it."""
    from ..observe import flight as _flight

    ep = server.endpoint
    _flight.note("chaos_kill", endpoint=ep)
    server.stop()
    return ep


def restart_server(endpoint: str, trainers: int = 1,
                   sync_timeout: float = 120.0,
                   recover_dir: Optional[str] = None):
    """Bring a fresh ParameterServer up on `endpoint`, recovering its
    shard (values + optimizer slots + sparse tables) from `recover_dir`
    when given — the crash/restart leg of the drill."""
    from ..observe import flight as _flight
    from ..pserver.server import ParameterServer

    srv = ParameterServer(endpoint, trainers=trainers,
                          sync_timeout=sync_timeout).start()
    if recover_dir is not None:
        srv.recover(recover_dir)
    _flight.note("chaos_restart", endpoint=endpoint,
                 recovered=recover_dir is not None)
    return srv
