"""fluid-ark: fault-tolerant training.

Four layers (reference analogs in each module's docstring):

- `checkpoint` — atomic, manifest-verified, rotated checkpoints
  (reference `CheckpointConfig`/`save_checkpoint` + checkpoint-notify,
  crash-hardened: tmp-dir + rename commit, sha256 MANIFEST, RNG cursors);
- `retry` — bounded exponential backoff with jitter for `PSClient` RPCs
  (reference gRPC client retry);
- `liveness` — heartbeat leases + the evicting sync barrier so a dead
  trainer degrades the world to N-1 instead of wedging it;
- `heartbeat` — the trainer-side lease renewal thread;
- `chaos` — the seed-deterministic fault injector that proves all of the
  above actually recovers (`tools/chaos_drill.py` drives it).
"""

from .checkpoint import (CheckpointConfig, CheckpointError,  # noqa: F401
                         atomic_file, file_sha256, latest_checkpoint,
                         list_checkpoints, load_checkpoint, read_manifest,
                         save_checkpoint, verify_checkpoint,
                         verify_sidecar, write_sidecar_manifest)
from .retry import NO_RETRY, RetryPolicy  # noqa: F401
from .liveness import (EvictingBarrier, LeaseTable,  # noqa: F401
                       QuorumLeaseTable)
from .heartbeat import HeartbeatThread  # noqa: F401
from . import chaos  # noqa: F401
