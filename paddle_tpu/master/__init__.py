"""Elastic data-sharding master (reference: go/master/ — task queue with
lease timeouts, failure budgets, and snapshot/recover; the P9 elastic
training capability)."""

from .service import Master  # noqa: F401
from .client import MasterClient  # noqa: F401
