"""Elastic data-sharding master (reference: go/master/ — task queue with
lease timeouts, failure budgets, and snapshot/recover; the P9 elastic
training capability). fluid-elastic: HA pairs behind the quorum arbiter
(`Master.start_replication` / `start_standby`) with exactly-once task
accounting across failover."""

from .service import DatasetMismatchError, Master  # noqa: F401
from .client import MasterClient  # noqa: F401
