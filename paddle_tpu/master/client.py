"""Master client: trainers pull task leases and stream records.

Capability parity with the reference Go client (reference:
go/master/client.go — GetTask/TaskFinished RPC, NextRecord :244 which
streams records out of the leased chunks; python ctypes wrapper
python/paddle/v2/master/client.py:29)."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Optional

from ..pserver import rpc


class MasterClient:
    def __init__(self, endpoint: str, retry_interval: float = 0.5):
        self.endpoint = endpoint
        self.retry_interval = retry_interval
        self._sock = None
        self._lock = threading.Lock()

    def _call(self, cmd, **payload):
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = rpc.connect(self.endpoint)
                rpc.send_msg(self._sock, (cmd, payload))
                status, value = rpc.recv_msg(self._sock)
            except (ConnectionError, EOFError, OSError):
                # drop the dead socket so the NEXT call reconnects — a
                # master restarted from its snapshot must be reachable
                # again without restarting the trainer (elastic contract)
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                raise
        if status != "ok":
            raise RuntimeError(f"master {self.endpoint} {cmd}: {value}")
        return value

    def set_dataset(self, payloads, chunks_per_task=1):
        return self._call("set_dataset", payloads=list(payloads),
                          chunks_per_task=chunks_per_task)

    def get_task(self):
        """Returns (status, task) where status is 'ok' | 'none' |
        'no_more'."""
        return self._call("get_task")

    def task_finished(self, task_id, epoch):
        return self._call("task_finished", task_id=task_id, epoch=epoch)

    def task_failed(self, task_id, epoch):
        return self._call("task_failed", task_id=task_id, epoch=epoch)

    def start_new_pass(self):
        return self._call("start_new_pass")

    def stats(self):
        return self._call("stats")

    def stop_master(self):
        try:
            self._call("stop")
        except (RuntimeError, ConnectionError, OSError):
            pass

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # -- record streaming (reference NextRecord :244) ----------------------
    def records(self, load_chunk: Callable[[Any], Iterable],
                stop_when_drained: bool = True):
        """Generator over records of leased tasks: pulls a task, yields
        every record `load_chunk(payload_item)` produces, then marks the
        task finished — a trainer crash mid-task leaves the lease to
        expire and the task is re-issued elsewhere (the elastic property)."""
        while True:
            status, task = self.get_task()
            if status == "no_more":
                if stop_when_drained:
                    return
                time.sleep(self.retry_interval)
                continue
            if status == "none":
                time.sleep(self.retry_interval)
                continue
            try:
                for item in task["payload"]:
                    for rec in load_chunk(item):
                        yield rec
            except GeneratorExit:
                raise
            except Exception:
                self.task_failed(task["task_id"], task["epoch"])
                raise
            self.task_finished(task["task_id"], task["epoch"])
