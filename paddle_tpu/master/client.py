"""Master client: trainers pull task leases and stream records.

Capability parity with the reference Go client (reference:
go/master/client.go — GetTask/TaskFinished RPC, NextRecord :244 which
streams records out of the leased chunks; python ctypes wrapper
python/paddle/v2/master/client.py:29).

fluid-elastic hardening: every call rides an ark `RetryPolicy`
(bounded exponential backoff + jitter, optional per-call deadline) so
a connection blip or a master restart is not a trainer death, and the
client FAILS OVER — a `redirect` reply (standby / fenced / deposed
master) or transport death of every known endpoint triggers
re-resolution of the RULING master: the configured standbys are polled
via `ha_status`, and with `quorum_endpoints` the arbiters themselves
are asked who holds the master lease (the holder id is the primary's
endpoint by convention), exactly like `PSClient` resolves a shard's
primary. The resolution loop waits out an in-flight promotion up to
`failover_s`.

Replay safety on this plane comes from the task-lease semantics, not
from a wire watermark: `task_finished`/`task_failed`/`task_returned`
are settlement-idempotent (a replayed settle of an already-settled
lease reads as stale and changes nothing), and a `get_task` whose
reply was lost merely strands one lease that times out and re-issues
under the task's failure budget — the documented duplicate-delivery
source. So every command retries through transport failures.
"""

from __future__ import annotations

import socket as _socket
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

from .. import flags as _flags
from ..ark.retry import RetryPolicy
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from ..pserver import rpc


class MasterClient:
    def __init__(self, endpoint: str, retry_interval: float = 0.5,
                 retry: Optional[RetryPolicy] = None,
                 deadline: Optional[float] = None,
                 standbys: Sequence[str] = (),
                 quorum_endpoints: Optional[Sequence[str]] = None,
                 quorum_resource: str = "master",
                 failover_s: float = 20.0):
        self.endpoint = endpoint
        self.retry_interval = retry_interval
        self.retry = retry if retry is not None else RetryPolicy()
        self.deadline = deadline if deadline is not None \
            else self.retry.deadline
        self.standbys = list(standbys)
        self.failover_s = float(failover_s)
        self._quorum_eps = list(quorum_endpoints or ())
        self._quorum_resource = quorum_resource
        self._quorum_client = None
        self._primary: Optional[str] = None   # ruling endpoint override
        self._sock = None                     # guarded_by: self._lock
        self._sock_ep: Optional[str] = None   # guarded_by: self._lock
        self._lock = threading.Lock()

    # -- transport ---------------------------------------------------------
    def _close_sock_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._sock_ep = None

    def _call_one(self, ep, cmd, payload, deadline):
        """One logical request against one endpoint, with the retry
        policy's backoff across transport failures. Caller holds no
        lock; socket state is guarded here."""
        policy = self.retry
        deadline_at = None if deadline is None \
            else time.monotonic() + deadline
        attempt = 0
        obs = _flags.get_flag("observe")
        while True:
            # fluid-horizon: one span context PER ATTEMPT (child of the
            # ambient trainer-step/caller span when one is active), sent
            # as the frame's optional third element so the master
            # handler's span parents here — retries are then distinct
            # child spans, not one blurred edge.
            att_ctx = _xray.child_of() if obs else None
            att_ts = time.time() if obs else 0.0
            att_t0 = time.perf_counter() if obs else 0.0
            try:
                # The lock covers exactly one request/response exchange:
                # the send/recv pair must be atomic on the shared socket,
                # but backoff between attempts must not hold it.
                with self._lock:
                    if self._sock is None or self._sock_ep != ep:
                        self._close_sock_locked()
                        remaining = 30.0 if deadline_at is None else \
                            max(0.05, deadline_at - time.monotonic())
                        self._sock = rpc.connect(ep, timeout=remaining)  # race_lint: ignore[blocking-under-lock] — single-connection wire serialization; the lock IS the socket's mutual exclusion
                        self._sock_ep = ep
                    if deadline_at is not None:
                        self._sock.settimeout(
                            max(0.05, deadline_at - time.monotonic()))
                    frame = (cmd, payload) if att_ctx is None else \
                        (cmd, payload, _xray.to_wire(att_ctx))
                    rpc.send_msg(self._sock, frame)  # race_lint: ignore[blocking-under-lock] — request/response pair must be atomic on the shared socket
                    status, value = rpc.recv_msg(self._sock)  # race_lint: ignore[blocking-under-lock] — request/response pair must be atomic on the shared socket
                    if deadline_at is not None:
                        self._sock.settimeout(None)
                    if att_ctx is not None:
                        _xray.record_span(
                            f"master_client:{cmd}", att_ctx, att_ts,
                            time.perf_counter() - att_t0, cat="rpc",
                            cmd=cmd, endpoint=ep, status=status)
                    return status, value
            except (ConnectionError, EOFError, OSError,
                    _socket.timeout) as e:
                if att_ctx is not None:
                    _xray.record_span(
                        f"master_client:{cmd}", att_ctx, att_ts,
                        time.perf_counter() - att_t0, cat="rpc",
                        cmd=cmd, endpoint=ep, error=type(e).__name__)
                with self._lock:
                    self._close_sock_locked()
                out_of_time = deadline_at is not None and \
                    time.monotonic() >= deadline_at
                if attempt >= policy.max_attempts or out_of_time:
                    raise
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "master_client_retries_total",
                        "master RPC attempts replayed after a "
                        "transport failure").inc(cmd=cmd)
                delay = policy.backoff(attempt)
                attempt += 1
                if deadline_at is not None:
                    delay = min(delay, max(
                        0.0, deadline_at - time.monotonic()))
                if delay:
                    time.sleep(delay)

    def _call(self, cmd, _deadline=..., **payload):
        if _deadline is ...:
            _deadline = self.deadline
        for _hop in range(4):
            ep = self._primary or self.endpoint
            try:
                status, value = self._call_one(ep, cmd, payload, _deadline)
            except (ConnectionError, EOFError, OSError, _socket.timeout):
                if self._resolve_master():
                    if _flags.get_flag("observe"):
                        _metrics.counter(
                            "master_client_failovers_total",
                            "master calls replayed at a re-resolved "
                            "ruling master").inc(cmd=cmd)
                    _flight.note("master_failover", cmd=cmd, frm=ep,
                                 to=self._primary or self.endpoint)
                    continue
                raise
            if status == "redirect":
                new = (value or {}).get("primary")
                if new and new != ep:
                    self._primary = None if new == self.endpoint else new
                    continue
                if self._resolve_master():
                    if _flags.get_flag("observe"):
                        _metrics.counter(
                            "master_client_failovers_total",
                            "master calls replayed at a re-resolved "
                            "ruling master").inc(cmd=cmd)
                    continue
                raise RuntimeError(
                    f"master {ep} {cmd}: NotMaster — no reachable ruling "
                    f"master ({value})")
            if status != "ok":
                raise RuntimeError(f"master {ep} {cmd}: {value}")
            return value
        raise RuntimeError(f"master {cmd}: the ruling master keeps moving "
                           f"(redirect loop)")

    # -- ruling-master resolution -----------------------------------------
    def _probe(self, ep):
        """Throwaway-socket ha_status probe (resolution is rare; it must
        not disturb the cached request socket)."""
        s = rpc.connect(ep, timeout=0.5)
        try:
            s.settimeout(1.0)
            rpc.send_msg(s, ("ha_status", {}))
            return rpc.recv_msg(s)
        finally:
            try:
                s.close()
            except OSError:
                pass

    def _quorum_holder(self) -> Optional[str]:
        if not self._quorum_eps:
            return None
        if self._quorum_client is None:
            from ..quorum import QuorumClient
            with self._lock:
                if self._quorum_client is None:
                    self._quorum_client = QuorumClient(self._quorum_eps,
                                                       deadline_s=1.0)
        try:
            rec = self._quorum_client.holder(self._quorum_resource)
        except Exception:   # noqa: BLE001 — resolution is best-effort
            return None
        return rec["holder"] if rec else None

    def _resolve_master(self, wait: bool = True) -> bool:
        """Find who RULES: poll ha_status across every known candidate
        (configured endpoint, standbys, the current mapping, and —
        leading the list — the arbiters' lease holder), adopting the
        first that reports `issuing`. A legacy master that rejects
        `ha_status` as unknown counts as a solo ruler. While some
        candidate still reports `standby` (a promotion may be landing)
        or a quorum route exists, keep polling up to `failover_s`."""
        cands: list = []
        for ep in ([self._primary] if self._primary else []) \
                + [self.endpoint] + self.standbys:
            if ep and ep not in cands:
                cands.append(ep)
        deadline = time.monotonic() + (self.failover_s if wait else 0.0)
        while True:
            hint = self._quorum_holder()
            if hint and hint not in cands:
                cands.insert(0, hint)
            saw_standby = False
            for ep in list(cands):
                try:
                    status, value = self._probe(ep)
                except (ConnectionError, EOFError, OSError,
                        _socket.timeout):
                    continue
                if status == "err" and "unknown command" in str(value):
                    role, is_issuing = "solo", True   # legacy master
                elif status != "ok":
                    continue
                else:
                    role = value.get("role")
                    is_issuing = bool(value.get("issuing"))
                    fed_by = value.get("primary")
                    if fed_by and fed_by not in cands:
                        cands.append(fed_by)
                if is_issuing:
                    self._primary = None if ep == self.endpoint else ep
                    _flight.note("master_resolved", primary=ep)
                    return True
                if role == "standby":
                    saw_standby = True
            if not wait or time.monotonic() >= deadline:
                return False
            if not saw_standby and not self._quorum_eps:
                return False   # nothing out there will ever promote
            time.sleep(0.25)

    # -- typed calls -------------------------------------------------------
    def set_dataset(self, payloads, chunks_per_task=1):
        return self._call("set_dataset", payloads=list(payloads),
                          chunks_per_task=chunks_per_task)

    def get_task(self):
        """Returns (status, task) where status is 'ok' | 'none' |
        'no_more'."""
        return self._call("get_task")

    def task_finished(self, task_id, epoch):
        return self._call("task_finished", task_id=task_id, epoch=epoch)

    def task_failed(self, task_id, epoch):
        return self._call("task_failed", task_id=task_id, epoch=epoch)

    def task_returned(self, task_id, epoch):
        """Hand a live lease back (clean trainer shutdown): the task
        re-queues IMMEDIATELY without burning its failure budget."""
        return self._call("task_returned", task_id=task_id, epoch=epoch)

    def start_new_pass(self):
        return self._call("start_new_pass")

    def stats(self):
        return self._call("stats")

    def ha_status(self):
        return self._call("ha_status")

    def stop_master(self):
        try:
            self._call("stop")
        except (RuntimeError, ConnectionError, OSError):
            pass

    def close(self):
        with self._lock:
            self._close_sock_locked()
        if self._quorum_client is not None:
            try:
                self._quorum_client.close()
            except Exception:   # noqa: BLE001
                pass

    # -- record streaming (reference NextRecord :244) ----------------------
    def records(self, load_chunk: Callable[[Any], Iterable],
                stop_when_drained: bool = True):
        """Generator over records of leased tasks: pulls a task, yields
        every record `load_chunk(payload_item)` produces, then marks the
        task finished — a trainer crash mid-task leaves the lease to
        expire and the task is re-issued elsewhere (the elastic
        property). A CLEAN close of the generator (trainer shutdown,
        `GeneratorExit`) RETURNS the in-flight lease instead of
        stranding it for the full `timeout_dur`, and without burning the
        task's failure budget — re-issue is immediate."""
        while True:
            status, task = self.get_task()
            if status == "no_more":
                if stop_when_drained:
                    return
                time.sleep(self.retry_interval)
                continue
            if status == "none":
                time.sleep(self.retry_interval)
                continue
            try:
                for item in task["payload"]:
                    for rec in load_chunk(item):
                        yield rec
            except GeneratorExit:
                try:
                    self.task_returned(task["task_id"], task["epoch"])
                except Exception:   # noqa: BLE001 — best-effort: the
                    pass            # lease timeout still covers it
                raise
            except Exception:
                self.task_failed(task["task_id"], task["epoch"])
                raise
            self.task_finished(task["task_id"], task["epoch"])
