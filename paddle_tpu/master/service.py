"""Elastic data-sharding master (P9), HA since fluid-elastic.

Capability parity with the reference Go master (reference:
go/master/service.go — partition :106, SetDataset :280, GetTask :368,
TaskFinished :411, TaskFailed :455, timeout re-queue via checkTimeoutFunc
:341, processFailedTask :313 with failureMax, etcd snapshot :207 /
recover :166 — and the etcd-leased election the Go master rides for HA).

TPU-native redesign: etcd is replaced by the fluid-quorum arbiter group
(election + fencing) plus an on-disk snapshot in the ark atomic idiom,
and the Go RPC by the same length-prefixed-pickle transport as the
parameter server (pserver/rpc.py). Task semantics are identical: a task
is a lease with a per-issue epoch counter — a trainer that dies mid-task
lets the lease time out and the task is re-issued; a task failing more
than `failure_max` times is discarded with a log line (reference
:323-331).

fluid-elastic HA (the haven idiom simplified — the state is small and
every record is idempotent):

- a PRIMARY (`start_replication`) forwards each task-lifecycle record
  (the moved task's full post-mutation row + which queue it landed in)
  to its STANDBY; the forwarder's batches double as the primary's lease
  renewal, and a full snapshot bootstraps or resyncs a standby that
  fell behind the bounded record log;
- the standby promotes ONLY behind a fencing epoch: with a
  `paddle_tpu/quorum/` arbiter group armed, on a strict-majority grant
  (a partitioned pair is an election the minority LOSES); without one,
  on primary-lease expiry under the documented crash-stop model;
- exactly-once task accounting across failover: a promoted standby
  KEEPS the replicated pending leases (task-id/epoch pairs intact) and
  restarts their lease clocks, so a surviving trainer's
  `task_finished(task_id, epoch)` still matches and is accepted exactly
  once. Only a task whose holder ALSO died expires and re-issues —
  the failure-budget path, the one documented duplicate-delivery
  source. A deposed primary answers task commands with a redirect
  (its fencing epoch is stale), never a state mutation;
- with no standby and no arbiters configured, the master is the
  legacy solo process, bit for bit.

Snapshots adopt the ark atomic idiom: tmp + `os.replace` + fsync with
an EMBEDDED sha256, and the previous serial is retained at
`<snapshot_path>.prev` — a torn or bit-rotted current snapshot falls
back to the previous serial instead of crashing recovery with a
JSONDecodeError (and with both serials gone, recovery starts empty
with a loud log line, never an exception).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

from .. import flags as _flags
from ..ark import checkpoint as ark_ckpt
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from ..pserver import rpc

logger = logging.getLogger(__name__)

#: commands that issue or settle task leases — only the RULING master
#: (solo, or an unfenced primary) may serve them; a standby or a
#: fenced/deposed primary answers with a redirect naming the ruler it
#: knows of, so a stale client re-resolves instead of mutating dead state
TASK_CMDS = frozenset({"get_task", "task_finished", "task_failed",
                       "task_returned", "set_dataset", "start_new_pass"})

ISSUED_METRIC = "master_tasks_issued_total"
FINISHED_METRIC = "master_tasks_finished_total"
FAILED_METRIC = "master_tasks_failed_total"
REISSUED_METRIC = "master_tasks_reissued_total"
DISCARDED_METRIC = "master_tasks_discarded_total"
RETURNED_METRIC = "master_tasks_returned_total"
PROMOTIONS_METRIC = "master_promotions_total"
STEP_DOWNS_METRIC = "master_step_downs_total"


class DatasetMismatchError(ValueError):
    """`set_dataset` was called with a dataset that differs from the one
    the master's (possibly recovered) state was partitioned from."""


class _Task:
    __slots__ = ("task_id", "payload", "epoch", "num_failure", "deadline")

    def __init__(self, task_id, payload, epoch=0, num_failure=0):
        self.task_id = task_id
        self.payload = payload
        self.epoch = epoch          # bumped on every (re-)issue; stale
        self.num_failure = num_failure
        self.deadline = 0.0         # lease expiry while pending

    def to_dict(self):
        return {"task_id": self.task_id, "payload": self.payload,
                "epoch": self.epoch, "num_failure": self.num_failure}

    @classmethod
    def from_dict(cls, d):
        return cls(d["task_id"], d["payload"], d["epoch"], d["num_failure"])


class Master:
    """Task-queue service. `timeout_dur` is the lease duration
    (reference timeoutDur); `failure_max` the per-task failure budget.
    `pulse_port` (with the observe flag on) starts the process's
    fluid-pulse health endpoint and registers a queue-state check."""

    def __init__(self, endpoint: str, snapshot_path: Optional[str] = None,
                 timeout_dur: float = 20.0, failure_max: int = 3,
                 check_interval: float = 1.0,
                 pulse_port: Optional[int] = None):
        self.endpoint = endpoint
        self.snapshot_path = snapshot_path
        self.timeout_dur = timeout_dur
        self.failure_max = failure_max
        self.check_interval = check_interval
        self._todo: List[_Task] = []          # guarded_by: self._lock
        self._pending: Dict[int, _Task] = {}  # guarded_by: self._lock
        self._done: List[_Task] = []          # guarded_by: self._lock
        self._dataset_fp: Optional[Dict] = None  # guarded_by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._conns: set = set()              # guarded_by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._epoch_pass = 0                  # guarded_by: self._lock
        # -- fluid-elastic HA state (all inert for the solo default) ----
        self.role = "solo"            # solo | primary | standby
        self.fence_epoch = 0
        self.lease_s = 2.0
        # primary whose quorum renew fails
        self._fenced = False          # guarded_by: self._lock
        self._auto_promote = True
        self._standby_endpoint: Optional[str] = None
        self._standby_sock: Optional[socket.socket] = None
        self._primary_endpoint: Optional[str] = None   # standby: my feed
        self._primary_expires = 0.0                    # monotonic
        self._quorum = None
        self._quorum_resource = "master"
        self._quorum_lease = None
        self._quorum_thread: Optional[threading.Thread] = None
        # primary: record sequence head
        self._ha_seq = 0              # guarded_by: self._lock
        # primary: standby's applied seq
        self._ha_acked = 0            # guarded_by: self._lock
        # [(seq, record)], bounded
        self._ha_log: List = []       # guarded_by: self._lock
        self._ha_log_cap = 1024
        self._ha_need_snap = False    # guarded_by: self._lock
        # standby unreachable, quorum held
        self._ha_degraded = False     # guarded_by: self._lock
        self._ha_flush_cond = threading.Condition()
        self._ha_dirty = threading.Event()
        # standby: replay watermark
        self._applied_seq = 0         # guarded_by: self._lock
        self._pulse_port_req = pulse_port
        self.pulse_port: Optional[int] = None
        if snapshot_path and (os.path.exists(snapshot_path)
                              or os.path.exists(snapshot_path + ".prev")):
            self._recover()

    # -- issuing verdict ---------------------------------------------------
    @property
    def issuing(self) -> bool:
        """True while THIS master may issue/settle task leases: a solo
        master always, a primary only while its quorum lease renews (a
        fenced or deposed primary holds). The chaos drills sample this
        across both members — at most one True at every instant."""
        return (self.role in ("solo", "primary")
                and not self._fenced  # race_lint: ignore[unguarded-read] — deliberately lock-free sampled verdict; callers tolerate one-tick staleness, and the chaos drills sample it at rate
                and not self._stop.is_set())

    # -- metrics (observe-gated; zero writes when the flag is off) ---------
    def _meter(self, name, help_, n=1, **labels):
        if _flags.get_flag("observe"):
            _metrics.counter(name, help_).inc(n, **labels)

    def _meter_queues_locked(self):
        if not _flags.get_flag("observe"):
            return
        ep = self.endpoint
        _metrics.gauge("master_tasks_todo",
                       "tasks waiting to be issued").set(
                           float(len(self._todo)), endpoint=ep)
        _metrics.gauge("master_tasks_pending",
                       "tasks out on a live lease").set(
                           float(len(self._pending)), endpoint=ep)
        _metrics.gauge("master_pass",
                       "data-pass counter").set(
                           float(self._epoch_pass), endpoint=ep)

    # -- dataset -----------------------------------------------------------
    @staticmethod
    def _dataset_fingerprint(payloads, chunks_per_task) -> Dict:
        """(count, sha) of the task set — how a recovered master tells
        `set_dataset` re-registration (idempotent no-op) apart from a
        caller holding a DIFFERENT dataset (a pointed error beats
        silently training on the wrong data)."""
        h = hashlib.sha256(str(int(chunks_per_task)).encode())
        for p in payloads:
            h.update(pickle.dumps(p, protocol=4))
        return {"count": len(payloads), "sha": h.hexdigest()}

    def set_dataset(self, payloads: List[Any], chunks_per_task: int = 1):
        """Partition payloads into tasks (reference partition :106).
        Idempotent across restarts (reference SetDataset :280 ignores
        re-registration once initialized) — but only for the SAME
        dataset: a payload-count/sha mismatch against recovered state
        raises instead of silently training on the wrong data."""
        payloads = list(payloads)
        fp = self._dataset_fingerprint(payloads, chunks_per_task)
        with self._lock:
            if self._todo or self._pending or self._done:
                if self._dataset_fp is None or fp == self._dataset_fp:
                    # legacy (unverifiable) state, or the identical
                    # dataset re-registered: the historical no-op
                    return
                raise DatasetMismatchError(
                    f"master {self.endpoint}: set_dataset mismatch — the "
                    f"(recovered) state was partitioned from "
                    f"{self._dataset_fp['count']} payloads (sha "
                    f"{self._dataset_fp['sha'][:12]}…) but the caller "
                    f"registered {fp['count']} (sha {fp['sha'][:12]}…); "
                    f"refusing to train on the wrong data. Delete the "
                    f"snapshot (or start a fresh master) to change "
                    f"datasets")
            tid = 0
            for i in range(0, len(payloads), chunks_per_task):
                self._todo.append(_Task(tid, payloads[i:i + chunks_per_task]))
                tid += 1
            self._dataset_fp = fp
            self._ha_mark_snapshot_locked()
            self._meter_queues_locked()
            self._snapshot_locked()

    # -- task lifecycle ----------------------------------------------------
    def get_task(self):
        with self._lock:
            if not self._todo:
                if not self._pending and self._done:
                    return ("no_more", None)       # pass finished
                return ("none", None)              # wait: leases pending
            t = self._todo.pop(0)
            t.epoch += 1
            t.deadline = time.time() + self.timeout_dur
            self._pending[t.task_id] = t
            self._ha_record_locked(t, "pending")
            issue_seq = self._ha_seq if (
                self.role == "primary"
                and self._standby_endpoint is not None) else 0
            if _flags.get_flag("observe"):
                _metrics.counter(ISSUED_METRIC,
                                 "task leases issued").inc()
                if t.epoch > 1:
                    _metrics.counter(
                        REISSUED_METRIC,
                        "task leases re-issued after a timeout, failure, "
                        "or clean return").inc()
                self._meter_queues_locked()
            self._snapshot_locked()
            reply = ("ok", {"task_id": t.task_id, "epoch": t.epoch,
                            "payload": t.payload})
        if issue_seq and not self._ha_flush(issue_seq):
            # the issue record could not reach the standby AND this
            # primary may no longer rule: the lease must not be handed
            # out (the promoted side would re-issue it blind, breaking
            # exactly-once). The trainer just waits; the stranded
            # pending row times out and re-issues at the ruler.
            return ("none", None)
        return reply

    def task_finished(self, task_id: int, epoch: int):
        with self._lock:
            t = self._pending.get(task_id)
            if t is None or t.epoch != epoch:
                return False                       # stale lease (re-issued)
            del self._pending[task_id]
            self._done.append(t)
            self._ha_record_locked(t, "done")
            self._meter(FINISHED_METRIC, "task leases finished")
            if not self._todo and not self._pending:
                logger.info("master: pass %d complete (%d tasks)",
                            self._epoch_pass, len(self._done))
            self._meter_queues_locked()
            self._snapshot_locked()
            return True

    def task_failed(self, task_id: int, epoch: int):
        with self._lock:
            t = self._pending.get(task_id)
            if t is None or t.epoch != epoch:
                return False
            del self._pending[task_id]
            self._meter(FAILED_METRIC,
                        "task leases reported failed (burns the task's "
                        "failure budget)")
            self._process_failed_locked(t)
            self._meter_queues_locked()
            self._snapshot_locked()
            return True

    def task_returned(self, task_id: int, epoch: int):
        """Clean lease return (fluid-elastic): a trainer shutting down
        mid-task hands the lease back so re-issue is IMMEDIATE, not
        timeout-bound — and without burning `num_failure` (an orderly
        departure is not a failure)."""
        with self._lock:
            t = self._pending.get(task_id)
            if t is None or t.epoch != epoch:
                return False
            del self._pending[task_id]
            self._todo.insert(0, t)   # head: it was already in flight
            self._ha_record_locked(t, "todo")
            self._meter(RETURNED_METRIC,
                        "task leases returned cleanly (trainer shutdown; "
                        "no failure budget burned)")
            self._meter_queues_locked()
            self._snapshot_locked()
            return True

    def _process_failed_locked(self, t: _Task):
        """reference processFailedTask :313: discard past failure_max."""
        t.num_failure += 1
        if t.num_failure > self.failure_max:
            logger.warning("master: task %d failed %d times, discarding",
                           t.task_id, t.num_failure)
            # a discarded task is SILENT DATA LOSS for the pass — always
            # in the black box, and the task_discard detector's evidence
            _flight.note("master_task_discard", task_id=t.task_id,
                         failures=t.num_failure, endpoint=self.endpoint)
            self._meter(DISCARDED_METRIC,
                        "tasks discarded after burning their failure "
                        "budget (records lost for this pass)")
            self._done.append(t)
            self._ha_record_locked(t, "done")
            return
        self._todo.append(t)
        self._ha_record_locked(t, "todo")

    def start_new_pass(self):
        """Re-queue everything for another data pass."""
        with self._lock:
            self._todo.extend(self._done)
            self._done = []
            for t in self._todo:
                t.num_failure = 0
            self._epoch_pass += 1
            self._ha_mark_snapshot_locked()
            self._meter_queues_locked()
            self._snapshot_locked()

    def _check_timeouts(self):
        while not self._stop.wait(self.check_interval):
            if not self.issuing:
                # a standby's replicated pending rows carry no local
                # deadlines, and a fenced primary must not mutate state
                # it may no longer own — only the ruler expires leases
                continue
            now = time.time()
            with self._lock:
                expired = [t for t in self._pending.values()
                           if t.deadline < now]
                for t in expired:
                    logger.info("master: task %d lease expired, re-queueing",
                                t.task_id)
                    del self._pending[t.task_id]
                    self._meter(FAILED_METRIC,
                                "task leases reported failed (burns the "
                                "task's failure budget)")
                    self._process_failed_locked(t)
                if expired:
                    self._meter_queues_locked()
                    self._snapshot_locked()

    # -- persistence (the etcd-snapshot analog, ark atomic idiom) ----------
    def _state_locked(self) -> Dict:
        return {"todo": [t.to_dict() for t in self._todo],
                "pending": [t.to_dict() for t in self._pending.values()],
                "done": [t.to_dict() for t in self._done],
                "pass": self._epoch_pass,
                "dataset_fp": self._dataset_fp}

    def _install_state_locked(self, state: Dict, recovered: bool = False):
        todo = [_Task.from_dict(d) for d in state["todo"]]
        pending = [_Task.from_dict(d) for d in state["pending"]]
        if recovered:
            # cold restart (reference recover :166): the pending leases
            # died with the previous PROCESS — back to todo. (A standby
            # installing a replicated snapshot keeps them pending: their
            # holders are still alive out there.)
            todo, pending = todo + pending, []
        self._todo = todo
        self._pending = {t.task_id: t for t in pending}
        self._done = [_Task.from_dict(d) for d in state["done"]]
        self._epoch_pass = state.get("pass", 0)
        self._dataset_fp = state.get("dataset_fp")

    @staticmethod
    def _state_sha(state: Dict) -> str:
        return hashlib.sha256(
            json.dumps(state, sort_keys=True).encode()).hexdigest()

    def _snapshot_locked(self):
        """Per-mutation durability is the contract (the etcd-write
        analog); the state is small by design. The payload is
        serialized ONCE — the sha is computed over the same canonical
        string that lands in the file, so the write is O(state) not
        O(2*state)."""
        if not self.snapshot_path:
            return
        body = json.dumps(self._state_locked(), sort_keys=True)
        sha = hashlib.sha256(body.encode()).hexdigest()
        # retain the previous serial: a crash mid-write (or bit rot in
        # the current file) falls back to it instead of losing the pass
        if os.path.exists(self.snapshot_path):
            try:
                os.replace(self.snapshot_path, self.snapshot_path + ".prev")
            except OSError:
                pass
        with ark_ckpt.atomic_file(self.snapshot_path, "w") as f:
            # {"sha256": ..., "state": <body>} — body verbatim, so the
            # recovery-side re-dump (sort_keys, default separators)
            # reproduces the hashed bytes exactly
            f.write('{"sha256": "%s", "state": %s}' % (sha, body))

    def _recover(self):
        """Load the newest INTACT serial — current, else `.prev` — and
        never crash: a corrupt corpus logs loudly and starts empty (the
        dataset must be re-registered), it does not take the process
        down with a JSONDecodeError."""
        with self._lock:
            for cand in (self.snapshot_path, self.snapshot_path + ".prev"):
                if not os.path.exists(cand):
                    continue
                try:
                    with open(cand) as f:
                        raw = json.load(f)
                except (ValueError, OSError) as e:
                    logger.warning("master: snapshot %s unreadable (%s); "
                                   "falling back to the previous serial",
                                   cand, e)
                    continue
                if isinstance(raw, dict) and "state" in raw \
                        and "sha256" in raw:
                    state = raw["state"]
                    if self._state_sha(state) != raw["sha256"]:
                        logger.warning(
                            "master: snapshot %s fails its embedded "
                            "sha256 (bit rot); falling back to the "
                            "previous serial", cand)
                        continue
                elif isinstance(raw, dict) and "todo" in raw:
                    state = raw   # legacy pre-elastic snapshot: no sha
                else:
                    logger.warning("master: snapshot %s has an "
                                   "unrecognized shape; skipping", cand)
                    continue
                try:
                    self._install_state_locked(state, recovered=True)
                except (KeyError, TypeError, ValueError) as e:
                    logger.warning("master: snapshot %s is structurally "
                                   "torn (%s); falling back", cand, e)
                    continue
                logger.info("master: recovered %d todo / %d done from %s",
                            len(self._todo), len(self._done), cand)
                return
            logger.warning(
                "master: NO intact snapshot at %s (nor .prev) — starting "
                "empty; the dataset must be re-registered",
                self.snapshot_path)

    # -- fluid-elastic: replication / election / fencing -------------------
    def _arm_quorum(self, quorum_endpoints, quorum_resource):
        from ..quorum import QuorumClient
        self._quorum = QuorumClient(
            list(quorum_endpoints), actor=self.endpoint,
            deadline_s=max(0.25, min(1.0, self.lease_s / 4.0)))
        self._quorum_resource = quorum_resource or "master"

    def start_replication(self, standby_endpoint: str, lease_s: float = 2.0,
                          quorum_endpoints=None,
                          quorum_resource: str = "master") -> "Master":
        """Arm this master as the PRIMARY of an HA pair: every task
        mutation is forwarded to `standby_endpoint` as a sequenced
        record (idle batches at lease/3 double as the lease renewal).
        With `quorum_endpoints`, the primacy itself is a majority-
        granted lease on `quorum_resource` — this master campaigns at
        startup (raising if it loses) and renews at lease/3; a failed
        renewal FENCES the task plane at once and local expiry steps
        the master down."""
        self.lease_s = float(lease_s)
        self._standby_endpoint = standby_endpoint
        if quorum_endpoints:
            self._arm_quorum(quorum_endpoints, quorum_resource)
        with self._lock:
            self.role = "primary"
            if self._quorum is not None:
                lease = self._quorum.campaign(
                    self._quorum_resource, self.endpoint, self.lease_s)
                if lease is None:
                    self.role = "solo"
                    raise RuntimeError(
                        f"master {self.endpoint}: lost the bootstrap "
                        f"election for {self._quorum_resource!r} — another "
                        f"master rules")
                self._quorum_lease = lease
                self.fence_epoch = lease.epoch
            else:
                self.fence_epoch = max(self.fence_epoch, 1)
            self._ha_mark_snapshot_locked()
        threading.Thread(target=self._forward_loop, daemon=True,
                         name=f"master-fwd@{self.endpoint}").start()
        if self._quorum is not None:
            self._start_quorum_loop()
        logger.info("master %s: primary at epoch %d, replicating to %s",
                    self.endpoint, self.fence_epoch, standby_endpoint)
        return self

    def start_standby(self, lease_s: float = 2.0, auto_promote: bool = True,
                      quorum_endpoints=None,
                      quorum_resource: str = "master") -> "Master":
        """Arm this master as a STANDBY: it applies the primary's record
        stream, redirects task commands, and promotes when the primary's
        lease expires — gated on a quorum majority grant when arbiters
        are configured (partition-safe), else on `auto_promote` under
        the documented crash-stop model."""
        self.lease_s = float(lease_s)
        self._auto_promote = bool(auto_promote)
        if quorum_endpoints:
            self._arm_quorum(quorum_endpoints, quorum_resource)
        with self._lock:
            self.role = "standby"
            # boot grace: give a live primary one lease to make contact
            self._primary_expires = time.monotonic() + self.lease_s
        threading.Thread(target=self._standby_monitor, daemon=True,
                         name=f"master-standby@{self.endpoint}").start()
        return self

    def _start_quorum_loop(self):
        if self._quorum_thread is None or not self._quorum_thread.is_alive():
            self._quorum_thread = threading.Thread(
                target=self._quorum_loop, daemon=True,
                name=f"master-quorum@{self.endpoint}")
            self._quorum_thread.start()

    # -- primary side ------------------------------------------------------
    def _ha_record_locked(self, t: _Task, queue: str):
        """One task-lifecycle record: the moved task's full row + its
        destination queue — idempotent by construction (applying twice
        lands the task in the same place)."""
        if self.role != "primary" or self._standby_endpoint is None:
            # a promoted master with no standby of its own (or a solo
            # master) has nobody to feed
            return
        self._ha_seq += 1
        rec = {"task": t.to_dict(), "queue": queue,
               "pass": self._epoch_pass}
        if _flags.get_flag("observe"):
            # fluid-horizon: the record remembers WHICH request caused it
            # (the master_server:* span active in this dispatch), so the
            # standby's apply span joins the trainer's trace across the
            # replication stream
            ctx = _xray.current()
            if ctx is not None:
                rec["trace"] = _xray.to_traceparent(ctx)
        self._ha_log.append((self._ha_seq, rec))
        if len(self._ha_log) > self._ha_log_cap:
            del self._ha_log[: len(self._ha_log) - self._ha_log_cap]
        self._ha_dirty.set()

    def _ha_flush(self, seq: int) -> bool:
        """The exactly-once linchpin: an ISSUED lease must be KNOWN to
        the standby before the trainer may act on it — otherwise a
        failover inside the in-flight window re-issues a task whose
        records are already being processed, and the duplicate is
        invisible to the task-epoch accounting. Blocks until the
        standby acked `seq` (sub-ms on a healthy pair), bounded by one
        lease. On timeout: if this primary STILL rules at the arbiters
        (not fenced), it DEGRADES to solo-forwarding — safe with a
        quorum armed, because the standby cannot win an election while
        our lease renews. WITHOUT arbiters the degrade keeps the pair's
        documented crash-stop model (exactly haven PR 12's): a
        pair-link-only partition can split the pair for its duration,
        because two nodes cannot tell "dead" from "unreachable" — arm a
        quorum (or `auto_promote=False`) where partitions are real. If
        this primary is fenced or deposed, the issue is refused
        (False).
        Settlement records (finish/fail/return) stay asynchronous: a
        lost settlement self-heals through the client's failover replay
        against the preserved pending lease."""
        deadline = time.monotonic() + self.lease_s
        with self._ha_flush_cond:
            while True:
                with self._lock:
                    flushed = (self._ha_acked >= seq or self._ha_degraded)
                if flushed:
                    break
                if self._stop.is_set() or self.role != "primary":
                    return False
                self._ha_dirty.set()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ha_flush_cond.wait(min(remaining, 0.05))
        with self._lock:
            if self._ha_acked >= seq or self._ha_degraded:
                return True
            if self._fenced or self.role != "primary":
                return False
            # the degrade verdict and the flag flip are one atomic step:
            # an unlocked write here raced _forward_once's locked
            # `_ha_degraded = False` on standby recovery
            self._ha_degraded = True
        logger.warning(
            "master %s: standby unreachable for %.1fs while the quorum "
            "lease still renews — DEGRADING to solo issue (the standby "
            "cannot win an election; it full-resyncs when it returns)",
            self.endpoint, self.lease_s)
        _flight.note("master_ha_degraded", endpoint=self.endpoint,
                     epoch=self.fence_epoch)
        return True

    def _ha_mark_snapshot_locked(self):
        """Whole-state mutations (set_dataset, new pass, recover) ship a
        full snapshot instead of per-task records."""
        if self.role != "primary" or self._standby_endpoint is None:
            return
        self._ha_seq += 1
        self._ha_need_snap = True
        self._ha_log.clear()
        self._ha_dirty.set()

    def _forward_loop(self):
        """Forward pending records (or a resync snapshot) to the standby;
        an idle iteration still sends an empty batch at lease/3 — the
        heartbeat that keeps the standby from promoting."""
        while not self._stop.is_set():
            self._ha_dirty.wait(timeout=self.lease_s / 3.0)
            if self._stop.is_set():
                return
            self._ha_dirty.clear()
            if self.role != "primary":
                continue
            try:
                self._forward_once()
            except (ConnectionError, EOFError, OSError,
                    socket.timeout) as e:
                logger.debug("master-fwd: standby %s unreachable: %s",
                             self._standby_endpoint, e)
                self._drop_standby_sock()

    def _forward_once(self):
        with self._lock:
            oldest = self._ha_log[0][0] if self._ha_log else self._ha_seq + 1
            need_snap = self._ha_need_snap or self._ha_acked < oldest - 1
            payload = {"epoch": self.fence_epoch, "primary": self.endpoint,
                       "lease_s": self.lease_s}
            if need_snap:
                payload["snapshot"] = self._state_locked()
                payload["base_seq"] = self._ha_seq
                payload["records"] = []   # the snapshot IS the head
            else:
                payload["records"] = [(s, r) for s, r in self._ha_log
                                      if s > self._ha_acked]
        sock = self._standby_sock
        if sock is None:
            sock = rpc.connect(self._standby_endpoint,
                               timeout=self.lease_s)
            self._standby_sock = sock
        sock.settimeout(self.lease_s)
        frame = ("m_replicate", payload)
        fctx = None
        if _flags.get_flag("observe"):
            # forwarder thread has no ambient context: each batch is a
            # fresh root span whose id rides the frame, so the standby's
            # master_server:m_replicate span parents here
            fctx = _xray.child_of()
            if fctx is not None:
                frame = ("m_replicate", payload, _xray.to_wire(fctx))
        fts = time.time()
        ft0 = time.monotonic()
        rpc.send_msg(sock, frame)
        status, value = rpc.recv_msg(sock)
        if fctx is not None:
            _xray.record_span("master_fwd:m_replicate", fctx, fts,
                              time.monotonic() - ft0, cat="ha",
                              records=len(payload["records"]),
                              snapshot="snapshot" in payload,
                              status=status)
        sock.settimeout(None)
        if status == "redirect":
            # the standby answers for a RULER at a higher epoch: this
            # primary was deposed while it could not see the quorum
            self._step_down("deposed_by_standby",
                            int((value or {}).get("epoch", 0)))
            return
        if status != "ok":
            logger.debug("master-fwd: standby rejected batch: %s", value)
            return
        if value.get("need_sync"):
            with self._lock:
                self._ha_need_snap = True
                self._ha_dirty.set()
            return
        with self._lock:
            self._ha_acked = max(self._ha_acked,
                                 int(value.get("applied_seq", 0)))
            if need_snap:
                self._ha_need_snap = False
            if self._ha_degraded:
                # the standby is back (and just acked a batch/snapshot):
                # leave solo-degraded mode — issues block on acks again
                logger.info("master %s: standby reachable again — "
                            "leaving degraded solo mode", self.endpoint)
                self._ha_degraded = False
            self._ha_log = [(s, r) for s, r in self._ha_log
                            if s > self._ha_acked]
        with self._ha_flush_cond:
            self._ha_flush_cond.notify_all()

    def _drop_standby_sock(self):
        s, self._standby_sock = self._standby_sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _quorum_loop(self):
        """Primary-side lease renewal at lease/3: a failed round fences
        the task plane at once (issuing False — no new leases, no
        settlements); local lease expiry steps the master down to an
        inert standby. Runs only while this master is primary."""
        while not self._stop.wait(self.lease_s / 3.0):
            if self.role != "primary" or self._quorum is None:
                continue
            lease = self._quorum_lease
            ok = False
            try:
                ok = lease is not None and self._quorum.renew(lease)
            except Exception as e:   # noqa: BLE001 — renewal best-effort
                logger.debug("master-quorum: renew failed: %s", e)
            if ok:
                with self._lock:
                    recovered = self._fenced
                    self._fenced = False
                if recovered:
                    logger.info("master %s: quorum renew recovered — "
                                "unfencing", self.endpoint)
                continue
            with self._lock:
                first = not self._fenced
                self._fenced = True
            if first:
                logger.warning("master %s: quorum renew FAILED — fencing "
                               "the task plane (step-down at local "
                               "expiry)", self.endpoint)
                _flight.note("master_fenced", endpoint=self.endpoint,
                             epoch=self.fence_epoch)
            if lease is None or not lease.live:
                self._step_down("quorum_lost", self.fence_epoch)

    def _step_down(self, reason: str, epoch: int):
        with self._lock:
            if self.role != "primary":
                return
            self.role = "standby"
            self._fenced = False
            self.fence_epoch = max(self.fence_epoch, int(epoch))
            # grace before this deposed node may campaign again
            self._primary_expires = time.monotonic() + self.lease_s
        logger.warning("master %s: STEPPED DOWN (%s) — now a standby at "
                       "epoch %d", self.endpoint, reason, self.fence_epoch)
        _flight.note("master_step_down", endpoint=self.endpoint,
                     reason=reason, epoch=self.fence_epoch)
        self._meter(STEP_DOWNS_METRIC,
                    "primary masters that abdicated", reason=reason)
        # a deposed primary must be able to promote again if the new
        # ruler dies later — the standby monitor does that
        threading.Thread(target=self._standby_monitor, daemon=True,
                         name=f"master-standby@{self.endpoint}").start()

    # -- standby side ------------------------------------------------------
    def _h_m_replicate(self, records=(), epoch=0, primary=None,
                       lease_s=2.0, snapshot=None, base_seq=0):
        epoch = int(epoch)
        with self._lock:
            if epoch < self.fence_epoch or (
                    self.role in ("solo", "primary")
                    and epoch <= self.fence_epoch):
                # a stale predecessor's stream — rejected UNCONDITIONALLY
                # below our fencing epoch, whatever our role or fence
                # state: a deposed primary reconnecting after a blip must
                # never overwrite the newer state this node replicated
                # (or ruled) at a higher epoch
                return ("redirect",
                        {"primary": self.endpoint if self.issuing
                         else self._primary_endpoint,
                         "epoch": self.fence_epoch})
            if self.role in ("solo", "primary"):
                # a RULER at a strictly higher epoch is feeding us: this
                # node was deposed (or is a bare master being adopted) —
                # become its standby
                self.role = "standby"
                self._fenced = False
            self._primary_endpoint = primary
            self._primary_expires = time.monotonic() + float(lease_s)
            self.lease_s = float(lease_s)
            self.fence_epoch = max(self.fence_epoch, epoch)
            if snapshot is not None:
                self._install_state_locked(snapshot)
                self._applied_seq = int(base_seq)
            obs = _flags.get_flag("observe")
            for seq, rec in records:
                seq = int(seq)
                if seq <= self._applied_seq:
                    continue                       # replayed duplicate
                if seq > self._applied_seq + 1:
                    return ("ok", {"need_sync": True,
                                   "applied_seq": self._applied_seq})
                # fluid-horizon: the record carries the traceparent of
                # the request that produced it — the standby's apply
                # span closes the trainer -> primary -> standby chain
                rctx = _xray.parse_traceparent(rec.get("trace")) \
                    if obs else None
                if rctx is not None:
                    with _xray.activate(rctx), \
                            _xray.span("master_apply:"
                                       + str(rec.get("queue")),
                                       cat="ha", seq=seq):
                        self._apply_record_locked(rec)
                else:
                    self._apply_record_locked(rec)
                self._applied_seq = seq
            self._snapshot_locked()
            return ("ok", {"applied_seq": self._applied_seq})

    def _apply_record_locked(self, rec: Dict):
        d = rec["task"]
        tid = d["task_id"]
        self._todo = [t for t in self._todo if t.task_id != tid]
        self._pending.pop(tid, None)
        self._done = [t for t in self._done if t.task_id != tid]
        t = _Task.from_dict(d)
        if rec["queue"] == "todo":
            self._todo.append(t)
        elif rec["queue"] == "pending":
            self._pending[tid] = t    # deadline re-armed at promotion
        else:
            self._done.append(t)
        self._epoch_pass = rec.get("pass", self._epoch_pass)

    def _standby_monitor(self):
        """Promote when the primary's lease expires — behind a quorum
        majority grant when arbiters are armed (a partitioned pair is an
        election this side must WIN, not assume), else on `auto_promote`
        under the crash-stop model."""
        while not self._stop.wait(min(self.lease_s / 3.0, 0.25)):
            if self.role != "standby":
                if self.role == "primary":
                    return   # promoted (or re-promoted); monitor retires
                continue
            if time.monotonic() < self._primary_expires:
                continue
            if self._quorum is not None:
                try:
                    lease = self._quorum.campaign(
                        self._quorum_resource, self.endpoint, self.lease_s,
                        max_rounds=1)
                except Exception as e:   # noqa: BLE001
                    logger.debug("master-standby: campaign failed: %s", e)
                    self._primary_expires = time.monotonic() + self.lease_s
                    continue
                if lease is None:
                    # lost: the primary lives on at the arbiters — back
                    # off a lease period before campaigning again
                    self._primary_expires = time.monotonic() + self.lease_s
                    continue
                self._quorum_lease = lease
                self._promote(lease.epoch, kind="quorum")
                return
            if self._auto_promote and self._primary_endpoint is not None:
                # crash-stop promotion requires that a primary FED this
                # standby at least once: a never-contacted standby (its
                # primary process still booting — the documented
                # standby-first deployment order) must not crown itself
                # over state it never had. Quorum-armed standbys may
                # campaign from boot: the election decides.
                self._promote(self.fence_epoch + 1, kind="lease_expiry")
                return

    def _promote(self, epoch: int, kind: str):
        with self._lock:
            if self.role == "primary":
                return
            self.role = "primary"
            self._fenced = False
            self.fence_epoch = max(self.fence_epoch, int(epoch))
            # exactly-once across failover: the replicated pending
            # leases SURVIVE — task-id/epoch pairs intact, so a
            # surviving trainer's task_finished still matches and is
            # accepted exactly once. Only the lease CLOCKS restart (the
            # old deadlines lived on the dead primary's clock).
            now = time.time()
            for t in self._pending.values():
                t.deadline = now + self.timeout_dur
            n_pending = len(self._pending)
            self._meter_queues_locked()
            self._snapshot_locked()
        logger.warning("master %s: PROMOTED to primary at epoch %d (%s; "
                       "%d pending leases preserved)", self.endpoint,
                       self.fence_epoch, kind, n_pending)
        _flight.note("master_promotion", endpoint=self.endpoint,
                     epoch=self.fence_epoch, promotion=kind,
                     pending=n_pending)
        self._meter(PROMOTIONS_METRIC,
                    "standby masters promoted to primary", kind=kind)
        if self._quorum is not None:
            self._start_quorum_loop()

    def ha_status(self) -> Dict:
        with self._lock:
            ruler = self.endpoint if self.issuing else self._primary_endpoint
            return {"role": self.role, "epoch": self.fence_epoch,
                    "issuing": self.issuing, "fenced": self._fenced,
                    "endpoint": self.endpoint, "primary": ruler,
                    "applied_seq": self._applied_seq,
                    "ha_seq": self._ha_seq, "ha_acked": self._ha_acked,
                    "todo": len(self._todo), "pending": len(self._pending),
                    "done": len(self._done), "pass": self._epoch_pass}

    # -- fluid-pulse -------------------------------------------------------
    def _pulse_check(self):
        st = self.ha_status()
        ok = not (st["role"] in ("solo", "primary") and st["fenced"])
        return (ok, st)

    # -- service loop (same wire protocol as the pserver) ------------------
    def start(self) -> "Master":
        host, port = rpc.parse_endpoint(self.endpoint)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        if port == 0:
            self.endpoint = f"{host}:{self._listener.getsockname()[1]}"
        self._listener.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"master@{self.endpoint}").start()
        threading.Thread(target=self._check_timeouts, daemon=True,
                         name="master-timeouts").start()
        if self._pulse_port_req is not None:
            from ..observe import health as _health
            from ..observe import pulse as _pulse
            self.pulse_port = _pulse.start_pulse(self._pulse_port_req)
            _health.get_engine().register_check(
                f"master_queues@{self.endpoint}", self._pulse_check,
                ready=True)
        return self

    def serve_forever(self):
        self.start()
        self._stop.wait()

    def stop(self, resign: bool = False):
        """Hard cut by default, like a killed process: listener AND
        every live connection die now (in-flight requests dropped
        unanswered — the chaos drills depend on SIGKILL semantics), and
        the quorum lease is NOT resigned — it expires at the arbiters,
        exactly as a real corpse's would. A PLANNED shutdown passes
        `resign=True` (tools/master_node.py's SIGTERM handler does) so
        the standby's election can start immediately instead of waiting
        out the lease."""
        if resign and self._quorum is not None \
                and self._quorum_lease is not None:
            try:
                self._quorum.resign(self._quorum_lease)
            except Exception:   # noqa: BLE001 — best-effort courtesy
                pass
        self._stop.set()
        self._ha_dirty.set()
        if self.pulse_port is not None:
            from ..observe import health as _health
            _health.get_engine().unregister_check(
                f"master_queues@{self.endpoint}")
            self.pulse_port = None
        self._drop_standby_sock()
        if self._quorum is not None:
            try:
                self._quorum.close()
            except Exception:   # noqa: BLE001
                pass
        if self._listener is not None:
            for f in ("shutdown", "close"):
                try:
                    (self._listener.shutdown(socket.SHUT_RDWR)
                     if f == "shutdown" else self._listener.close())
                except OSError:
                    pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            for f in ("shutdown", "close"):
                try:
                    (c.shutdown(socket.SHUT_RDWR) if f == "shutdown"
                     else c.close())
                except OSError:
                    pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            # mconn@ names carry the chaos actor identity (server-side
            # replies attribute to this master's endpoint)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"mconn@{self.endpoint}").start()

    def _dispatch(self, cmd, p):
        if cmd in TASK_CMDS and not self.issuing:
            # a standby knows its feeder; a fenced/deposed primary may
            # not know the new ruler — the client resolves through the
            # arbiters either way
            hint = self._primary_endpoint if self.role == "standby" \
                else None
            return ("redirect", {"primary": hint,
                                 "epoch": self.fence_epoch})
        if cmd == "get_task":
            return ("ok", self.get_task())
        if cmd == "task_finished":
            return ("ok", self.task_finished(**p))
        if cmd == "task_failed":
            return ("ok", self.task_failed(**p))
        if cmd == "task_returned":
            return ("ok", self.task_returned(**p))
        if cmd == "set_dataset":
            return ("ok", self.set_dataset(**p))
        if cmd == "start_new_pass":
            return ("ok", self.start_new_pass())
        if cmd == "stats":
            with self._lock:
                return ("ok", {"todo": len(self._todo),
                               "pending": len(self._pending),
                               "done": len(self._done)})
        if cmd == "ha_status":
            return ("ok", self.ha_status())
        if cmd == "m_replicate":
            return self._h_m_replicate(**p)
        if cmd == "stop":
            return ("ok", None)
        return ("err", f"unknown command {cmd!r}")

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    msg = rpc.recv_msg(conn)
                except (ConnectionError, EOFError, OSError):
                    return
                if self._stop.is_set():
                    return   # dead process: drop the request unanswered
                try:
                    # (cmd, payload[, meta]): the optional meta dict
                    # carries the caller's traceparent (fluid-horizon) —
                    # legacy 2-tuple frames keep working
                    cmd, p = msg[0], msg[1]
                    meta = msg[2] if len(msg) >= 3 else None
                except (TypeError, IndexError):
                    try:
                        rpc.send_msg(conn, ("err", "MalformedFrame: "
                                            "expected (cmd, payload[, "
                                            "meta])"))
                        continue
                    except (ConnectionError, OSError):
                        return
                wctx = _xray.from_wire(meta) \
                    if meta and _flags.get_flag("observe") else None
                try:
                    if wctx is not None:
                        with _xray.activate(wctx), \
                                _xray.span(f"master_server:{cmd}",
                                           cat="rpc", cmd=cmd,
                                           endpoint=self.endpoint,
                                           role=self.role):
                            reply = self._dispatch(cmd, p)
                    else:
                        reply = self._dispatch(cmd, p)
                except Exception as e:
                    reply = ("err", f"{type(e).__name__}: {e}")
                try:
                    rpc.send_msg(conn, reply)
                except (ConnectionError, OSError):
                    return
                if cmd == "stop":
                    self.stop()
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()
