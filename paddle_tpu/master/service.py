"""Elastic data-sharding master (P9).

Capability parity with the reference Go master (reference:
go/master/service.go — partition :106, SetDataset :280, GetTask :368,
TaskFinished :411, TaskFailed :455, timeout re-queue via checkTimeoutFunc
:341, processFailedTask :313 with failureMax, etcd snapshot :207 /
recover :166).

TPU-native redesign: etcd is replaced by an on-disk JSON snapshot (the
cluster filesystem is the coordination substrate available here), and the
Go RPC by the same length-prefixed-pickle transport as the parameter
server (pserver/rpc.py). Task semantics are identical: a task is a lease
with an epoch counter — a trainer that dies mid-task simply lets the lease
time out and the task is re-issued; a task failing more than `failure_max`
times is discarded with a log line (reference :323-331)."""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from ..pserver import rpc

logger = logging.getLogger(__name__)


class _Task:
    __slots__ = ("task_id", "payload", "epoch", "num_failure", "deadline")

    def __init__(self, task_id, payload, epoch=0, num_failure=0):
        self.task_id = task_id
        self.payload = payload
        self.epoch = epoch          # bumped on every (re-)issue; stale
        self.num_failure = num_failure
        self.deadline = 0.0         # lease expiry while pending

    def to_dict(self):
        return {"task_id": self.task_id, "payload": self.payload,
                "epoch": self.epoch, "num_failure": self.num_failure}

    @classmethod
    def from_dict(cls, d):
        return cls(d["task_id"], d["payload"], d["epoch"], d["num_failure"])


class Master:
    """Task-queue service. `timeout_dur` is the lease duration
    (reference timeoutDur); `failure_max` the per-task failure budget."""

    def __init__(self, endpoint: str, snapshot_path: Optional[str] = None,
                 timeout_dur: float = 20.0, failure_max: int = 3,
                 check_interval: float = 1.0):
        self.endpoint = endpoint
        self.snapshot_path = snapshot_path
        self.timeout_dur = timeout_dur
        self.failure_max = failure_max
        self.check_interval = check_interval
        self._todo: List[_Task] = []
        self._pending: Dict[int, _Task] = {}
        self._done: List[_Task] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._epoch_pass = 0
        if snapshot_path and os.path.exists(snapshot_path):
            self._recover()

    # -- dataset ----------------------------------------------------------
    def set_dataset(self, payloads: List[Any], chunks_per_task: int = 1):
        """Partition payloads into tasks (reference partition :106).
        Idempotent across restarts: only applies when the queue is empty
        and nothing was recovered (reference SetDataset :280 ignores
        re-registration once initialized)."""
        with self._lock:
            if self._todo or self._pending or self._done:
                return
            tid = 0
            for i in range(0, len(payloads), chunks_per_task):
                self._todo.append(_Task(tid, payloads[i:i + chunks_per_task]))
                tid += 1
            self._snapshot_locked()

    # -- task lifecycle ---------------------------------------------------
    def get_task(self):
        with self._lock:
            if not self._todo:
                if not self._pending and self._done:
                    return ("no_more", None)       # pass finished
                return ("none", None)              # wait: leases pending
            t = self._todo.pop(0)
            t.epoch += 1
            t.deadline = time.time() + self.timeout_dur
            self._pending[t.task_id] = t
            self._snapshot_locked()
            return ("ok", {"task_id": t.task_id, "epoch": t.epoch,
                           "payload": t.payload})

    def task_finished(self, task_id: int, epoch: int):
        with self._lock:
            t = self._pending.get(task_id)
            if t is None or t.epoch != epoch:
                return False                       # stale lease (re-issued)
            del self._pending[task_id]
            self._done.append(t)
            if not self._todo and not self._pending:
                logger.info("master: pass %d complete (%d tasks)",
                            self._epoch_pass, len(self._done))
            self._snapshot_locked()
            return True

    def task_failed(self, task_id: int, epoch: int):
        with self._lock:
            t = self._pending.get(task_id)
            if t is None or t.epoch != epoch:
                return False
            del self._pending[task_id]
            self._process_failed_locked(t)
            self._snapshot_locked()
            return True

    def _process_failed_locked(self, t: _Task):
        """reference processFailedTask :313: discard past failure_max."""
        t.num_failure += 1
        if t.num_failure > self.failure_max:
            logger.warning("master: task %d failed %d times, discarding",
                           t.task_id, t.num_failure)
            self._done.append(t)
            return
        self._todo.append(t)

    def start_new_pass(self):
        """Re-queue everything for another data pass."""
        with self._lock:
            self._todo.extend(self._done)
            self._done = []
            for t in self._todo:
                t.num_failure = 0
            self._epoch_pass += 1
            self._snapshot_locked()

    def _check_timeouts(self):
        while not self._stop.wait(self.check_interval):
            now = time.time()
            with self._lock:
                expired = [t for t in self._pending.values()
                           if t.deadline < now]
                for t in expired:
                    logger.info("master: task %d lease expired, re-queueing",
                                t.task_id)
                    del self._pending[t.task_id]
                    self._process_failed_locked(t)
                if expired:
                    self._snapshot_locked()

    # -- persistence (etcd analog) ----------------------------------------
    def _snapshot_locked(self):
        if not self.snapshot_path:
            return
        state = {"todo": [t.to_dict() for t in self._todo],
                 "pending": [t.to_dict() for t in self._pending.values()],
                 "done": [t.to_dict() for t in self._done],
                 "pass": self._epoch_pass}
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, self.snapshot_path)

    def _recover(self):
        """reference recover :166: pending tasks go back to todo — their
        leases died with the previous master process."""
        with open(self.snapshot_path) as f:
            state = json.load(f)
        self._todo = [_Task.from_dict(d)
                      for d in state["todo"] + state["pending"]]
        self._done = [_Task.from_dict(d) for d in state["done"]]
        self._epoch_pass = state.get("pass", 0)
        logger.info("master: recovered %d todo / %d done from %s",
                    len(self._todo), len(self._done), self.snapshot_path)

    # -- service loop (same wire protocol as the pserver) ------------------
    def start(self) -> "Master":
        host, port = rpc.parse_endpoint(self.endpoint)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        if port == 0:
            self.endpoint = f"{host}:{self._listener.getsockname()[1]}"
        self._listener.listen(64)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"master@{self.endpoint}").start()
        threading.Thread(target=self._check_timeouts, daemon=True,
                         name="master-timeouts").start()
        return self

    def serve_forever(self):
        self.start()
        self._stop.wait()

    def stop(self):
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    cmd, p = rpc.recv_msg(conn)
                except (ConnectionError, EOFError, OSError):
                    return
                try:
                    if cmd == "get_task":
                        reply = ("ok", self.get_task())
                    elif cmd == "task_finished":
                        reply = ("ok", self.task_finished(**p))
                    elif cmd == "task_failed":
                        reply = ("ok", self.task_failed(**p))
                    elif cmd == "set_dataset":
                        reply = ("ok", self.set_dataset(**p))
                    elif cmd == "start_new_pass":
                        reply = ("ok", self.start_new_pass())
                    elif cmd == "stats":
                        with self._lock:
                            reply = ("ok", {"todo": len(self._todo),
                                            "pending": len(self._pending),
                                            "done": len(self._done)})
                    elif cmd == "stop":
                        reply = ("ok", None)
                    else:
                        reply = ("err", f"unknown command {cmd!r}")
                except Exception as e:
                    reply = ("err", f"{type(e).__name__}: {e}")
                rpc.send_msg(conn, reply)
                if cmd == "stop":
                    self.stop()
                    return
        finally:
            conn.close()
