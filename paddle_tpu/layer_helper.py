"""LayerHelper: shared plumbing for the layers DSL.

Capability parity with reference python/paddle/fluid/layer_helper.py:
creates parameters (appending initializer ops to the startup program),
temporary variables, and ops; runs build-time shape inference through the op
registry (which derives it from the JAX lowering rules via eval_shape).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .core import ir, registry
from .core.ir import seqlen_var_name
from . import initializer as init
from . import unique_name
from .param_attr import ParamAttr


def _to_var(block, x):
    if isinstance(x, ir.Variable):
        return x
    return block.var(str(x))


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name else unique_name.generate(layer_type)

    @property
    def main_program(self) -> ir.Program:
        return ir.default_main_program()

    @property
    def startup_program(self) -> ir.Program:
        return ir.default_startup_program()

    @property
    def block(self) -> ir.Block:
        return self.main_program.current_block()

    # -- inputs ----------------------------------------------------------
    def input(self, name="input"):
        inputs = self.kwargs.get(name)
        if isinstance(inputs, (list, tuple)):
            return [_to_var(self.block, i) for i in inputs]
        return _to_var(self.block, inputs)

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        battr = self.kwargs.get("bias_attr")
        if battr is False:
            return False
        return ParamAttr._to_attr(battr)

    # -- variable creation ----------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None, stop_gradient=False) -> ir.Parameter:
        attr = ParamAttr._to_attr(attr)
        name = attr.name or unique_name.generate(f"{self.name}.w")
        gb = self.main_program.global_block()
        if name in gb.vars:
            return gb.vars[name]
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = (init._global_bias_initializer() if is_bias
                           else init._global_weight_initializer())
        param = gb.create_parameter(
            name, shape, dtype, trainable=attr.trainable,
            regularizer=attr.regularizer, gradient_clip=attr.gradient_clip,
            sharding=attr.sharding, stop_gradient=stop_gradient)
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        # mirror into startup program + append its initializer op there
        sb = self.startup_program.global_block()
        if name not in sb.vars:
            svar = sb.create_parameter(name, shape, dtype, trainable=attr.trainable)
            initializer(svar, sb)
        return param

    def create_variable_for_type_inference(self, dtype="float32",
                                           stop_gradient=False) -> ir.Variable:
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            shape=(), dtype=dtype, stop_gradient=stop_gradient)

    # Backwards-compat alias (reference helper name).
    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, name=None, shape=(1,), dtype="float32",
                               persistable=False, stop_gradient=True) -> ir.Variable:
        gb = self.main_program.global_block()
        return gb.create_var(name=name or unique_name.generate(f"{self.name}.global"),
                             shape=shape, dtype=dtype, persistable=persistable,
                             stop_gradient=stop_gradient)

    def set_variable_initializer(self, var, initializer):
        sb = self.startup_program.global_block()
        if var.name not in sb.vars:
            svar = sb.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                                 persistable=True)
            initializer(svar, sb)

    # -- op creation with shape inference --------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> ir.Operator:
        op = self.block.append_op(type, inputs, outputs, attrs)
        self._infer_shapes(op)
        return op

    def _infer_shapes(self, op: ir.Operator):
        if not registry.is_registered(op.type):
            return
        block = self.block
        ins = {}
        try:
            for slot, names in op.inputs.items():
                pairs = []
                for n in names:
                    v = block.var(n)
                    pairs.append((v.shape, v.dtype))
                ins[slot] = pairs
            result = registry.infer_op_shapes(op.type, op.attrs, ins)
        except NotImplementedError:
            raise
        except Exception:
            return  # runtime shapes remain authoritative
        for slot, names in op.outputs.items():
            if slot not in result:
                continue
            for n, (shape, dtype) in zip(names, result[slot]):
                if n in block.vars:
                    v = block.vars[n]
                    if not v.shape or v.shape == ():
                        v.shape = shape
                        v.dtype = dtype

    # -- activation sugar -------------------------------------------------
    def append_activation(self, input_var: ir.Variable) -> ir.Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(act_type, inputs={"X": [input_var.name]},
                       outputs={"Out": [out.name]}, attrs=act)
        out.lod_level = input_var.lod_level
        return out

    def to_variable(self, x):
        return _to_var(self.block, x)

    # -- sequence plumbing -------------------------------------------------
    def ensure_seqlen_var(self, var: ir.Variable,
                          level: int = 0) -> Optional[ir.Variable]:
        """Materialize the lengths companion for LoD level `level` of a
        lod-carrying var so sequence ops can wire it as an explicit input.
        Level 0 is the outermost (shape [B]); level 1 the nested inner
        lengths (shape [B, S])."""
        if var.lod_level <= level:
            return None
        name = seqlen_var_name(var.name, level)
        blk = var.block
        if name in blk.vars:
            return blk.vars[name]
        shape = (-1,) * (level + 1)
        return blk.create_var(name=name, shape=shape, dtype="int32",
                              stop_gradient=True)
