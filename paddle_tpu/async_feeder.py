"""Asynchronous input pipeline: the reference's py_reader / double_buffer
analog (reference: python/paddle/fluid/layers/io.py:449 `py_reader`,
operators/reader/create_double_buffer_reader_op.cc,
reader/lod_tensor_blocking_queue.h).

TPU-native redesign: a background thread pulls batches from a python reader
and converts them via DataFeeder (host-side work) into a bounded queue; the
consumer thread issues the `jax.device_put` at yield time — PJRT enqueues
the copy asynchronously, so it still overlaps the previous step's compute
(the double-buffer property) without driving the device from two threads.
No in-graph reader ops are needed because feeds enter the jitted step as
arguments.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterable, Optional

import jax

from . import flags as _flags
from .observe import metrics as _metrics


class AsyncFeeder:
    """`for feed in AsyncFeeder(feeder, reader, capacity=4): exe.run(feed=feed)`

    feeder: DataFeeder (or any fn batch->feed dict); reader: batched reader
    (yields lists of samples). device/sharding: optional placement applied
    ahead of the step (ParallelExecutor passes its batch sharding).
    """

    def __init__(self, feeder, reader: Callable[[], Iterable], capacity: int = 4,
                 device=None, sharding=None, pad_to: int = 0, prepared=None):
        self._feeder = feeder
        self._reader = reader
        self._capacity = capacity
        self._device = device
        self._sharding = sharding
        self._pad_to = pad_to
        if prepared is not None and device is None and sharding is None:
            # pair with an Executor.prepare() handle: transfers target the
            # device the prepared step dispatches to, so each batch's H2D
            # is enqueued (async under PJRT) while the PREVIOUS prepared
            # step still runs — host dispatch and feed placement overlap
            # the step end-to-end
            self._device = prepared.device

    def _convert(self, batch) -> Dict:
        """Host-side conversion only — runs on the producer thread."""
        feed = (self._feeder.feed(batch, pad_to=self._pad_to)
                if hasattr(self._feeder, "feed") else self._feeder(batch))
        return feed

    def _place(self, feed) -> Dict:
        """Device placement at yield time, on the CONSUMER thread: PJRT
        device_put is an async enqueue, so the copy still overlaps the
        previous step's compute, while issuing transfers from a second
        thread is avoided (runtimes — the axon tunnel in particular — may
        serialize or deadlock on concurrent stream use)."""
        target = self._sharding or self._device
        if target is not None:
            out = {}
            for k, v in feed.items():
                if isinstance(v, tuple):
                    out[k] = tuple(jax.device_put(x, target) for x in v)
                else:
                    out[k] = jax.device_put(v, target)
            return out
        return feed

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        end = object()
        err = []
        stop = threading.Event()

        def producer():
            try:
                for batch in self._reader():
                    item = self._convert(batch)
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.2)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return  # consumer abandoned the iteration
            except Exception as e:  # surface reader errors on the consumer
                err.append(e)
            finally:
                # the end sentinel must be DELIVERED, not best-effort: a
                # full queue here (consumer slower than producer) would
                # drop it and hang the consumer after it drains
                while not stop.is_set():
                    try:
                        q.put(end, timeout=0.2)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                if _flags.get_flag("observe"):
                    # queue-depth/starvation gauges: a consumer wait with
                    # an empty queue means the producer (reader + host
                    # conversion) is the bottleneck — the overlap the
                    # feeder exists to provide is NOT happening
                    t0 = time.perf_counter()
                    starved = q.empty()
                    item = q.get()
                    wait = time.perf_counter() - t0
                    _metrics.gauge(
                        "feeder_queue_depth",
                        "batches buffered ahead of the consumer").set(
                            q.qsize())
                    if item is not end:
                        _metrics.counter(
                            "feeder_batches_total",
                            "batches delivered to the consumer").inc()
                        _metrics.histogram(
                            "feeder_consumer_wait_seconds",
                            "time the consumer blocked waiting for a batch"
                        ).observe(wait)
                        if starved:
                            _metrics.counter(
                                "feeder_starvation_total",
                                "consumer arrivals that found the queue "
                                "empty (producer-bound pipeline)").inc()
                else:
                    item = q.get()
                if item is end:
                    break
                yield self._place(item)
        finally:
            # on break/close: release the producer and drop buffered batches
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        if err:
            raise err[0]
