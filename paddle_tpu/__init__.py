"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
Fluid-era PaddlePaddle (reference: coslian/Paddle v0.14.0).

Architecture (see SURVEY.md for the reference blueprint):
  - Program/Block/Op IR built from a layers DSL (core/ir.py)
  - ops are JAX lowering rules; shape inference via eval_shape (core/registry.py)
  - program-level autodiff emitting generic vjp grad ops (core/backward.py)
  - Executor compiles whole blocks into single XLA computations (core/executor.py)
  - data parallelism via pjit/GSPMD over a device Mesh (parallel/)
"""

import os as _os

# Honor JAX_PLATFORMS=cpu at import: some environments (the axon dev
# tunnel) force-register their accelerator backend from sitecustomize
# and IGNORE the env var, so a subprocess asking for CPU (pserver
# services, multi-process tests, the embedded C-ABI interpreter) would
# silently initialize — and hang on, when the tunnel is down — the
# accelerator backend instead. config.update wins over the sitecustomize
# override; it must run before the first backend use, which importing
# this package is about to cause.
if _os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

from .core import ir as _ir
from .core.ir import (Program, program_guard, default_main_program,  # noqa: F401
                      default_startup_program, Variable, Parameter, Operator)
from .core.executor import (Executor, PreparedProgram, Scope,  # noqa: F401
                            global_scope, CPUPlace, TPUPlace, CUDAPlace,
                            EOFException, scope_guard, _switch_scope,
                            fetch_var)
from .core.backward import append_backward, calc_gradient  # noqa: F401

from . import ops  # noqa: F401  (registers all lowering rules)
from . import wire  # noqa: F401  (fluid-wire codecs + comm_quant op)
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import unique_name  # noqa: F401
from . import nets  # noqa: F401
from . import metrics  # noqa: F401
from . import io  # noqa: F401
from . import profiler  # noqa: F401
from . import debugger  # noqa: F401
from . import evaluator  # noqa: F401
from . import average  # noqa: F401
from . import annotations  # noqa: F401
from . import contrib  # noqa: F401
from . import graphviz  # noqa: F401
from . import net_drawer  # noqa: F401
from . import op  # noqa: F401
from . import default_scope_funcs  # noqa: F401
from . import recordio_writer  # noqa: F401
from .recordio_writer import (convert_reader_to_recordio_file,  # noqa: F401
                              convert_reader_to_recordio_files)
from . import ir_pass  # noqa: F401
from . import analysis  # noqa: F401
from .analysis import ProgramVerificationError  # noqa: F401
from . import enforce  # noqa: F401
from . import lod_tensor  # noqa: F401
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor  # noqa: F401
from .enforce import EnforceNotMet  # noqa: F401
from . import flags  # noqa: F401
from .flags import get_flag, set_flag  # noqa: F401
from . import observe  # noqa: F401  (fluid-scope runtime telemetry)
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .async_feeder import AsyncFeeder  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from .parallel.parallel_executor import (ParallelExecutor,  # noqa: F401
                                         BuildStrategy, ExecutionStrategy)
from . import backward  # noqa: F401
from . import transpiler  # noqa: F401
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .transpiler import memory_optimize, release_memory, InferenceTranspiler  # noqa: F401
from . import distributed  # noqa: F401
from . import pserver  # noqa: F401
from . import ark  # noqa: F401  (fluid-ark fault-tolerant training)
from . import serve  # noqa: F401  (fluid-serve TPU inference serving)
from . import fleet  # noqa: F401  (fluid-fleet multi-replica serving tier)
from . import haven  # noqa: F401  (fluid-haven replicated PS plane)
from . import master  # noqa: F401
from . import recordio  # noqa: F401
from .trainer import (Trainer, Inferencer, CheckpointConfig,  # noqa: F401
                      BeginEpochEvent, EndEpochEvent, BeginStepEvent,
                      EndStepEvent, save_checkpoint, load_checkpoint)

__version__ = "0.1.0"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    import jax
    return any(d.platform != "cpu" for d in jax.devices())


def tpu_device_count() -> int:
    import jax
    return len(jax.devices())


def get_var(name, program=None):
    """Look up a Variable by name in a program's global block (reference
    framework.py get_var)."""
    program = program or default_main_program()
    v = program.global_block()._find_var_recursive(name)
    if v is None:
        raise ValueError(f"get_var: no variable named {name!r}")
    return v
