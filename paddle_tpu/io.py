"""Model / variable save-load.

Capability parity with reference python/paddle/fluid/io.py:
save_vars/save_params/save_persistables (:86-290), load_vars/load_params/
load_persistables (:292-455), save_inference_model (:551),
load_inference_model (:654). The reference serializes per-variable
LoDTensor streams via save/load ops; the TPU-native design serializes the
scope arrays to one .npz per save (or one file per var with
`filename=None`-style layout preserved) and the Program to JSON.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import uuid
from typing import List, Optional, Sequence

import numpy as np

from .ark.checkpoint import atomic_file, file_sha256
from .core import ir
from .core.executor import Executor, Scope, global_scope

logger = logging.getLogger(__name__)

MODEL_FILENAME = "__model__"
# fluid-decode: the autoregressive decode-step program of a generative
# model rides next to the prefill `__model__` in the same atomic dir
DECODE_FILENAME = "__decode__"
PARAMS_SUFFIX = ".npy"
# same name + schema as ark's checkpoint manifest, so
# `ark.checkpoint.verify_checkpoint(model_dir)` works on a model dir too
MODEL_MANIFEST = "MANIFEST.json"


class ModelIntegrityError(RuntimeError):
    """A saved inference-model dir fails sha256 verification against its
    MANIFEST.json — bit rot or a torn copy. The message names the first
    corrupt/missing file so operators can see WHAT rotted, and loaders
    (serve.ModelRegistry) can refuse the dir before deserializing any of
    it."""


def _is_persistable(var: ir.Variable) -> bool:
    # KV-cache state is persistable ACROSS STEPS but not across saves:
    # serializing gigabytes of transient cache (or trying to load it
    # back) would be wrong both ways — the serving registry zeros it
    # fresh from the manifest's decode signature at load
    return var.persistable and not var.is_data \
        and var.kind == ir.VarKind.DENSE_TENSOR \
        and not var.name.endswith(ir.KV_CACHE_SUFFIX)


def _is_parameter(var: ir.Variable) -> bool:
    return isinstance(var, ir.Parameter)


def _collect(program: ir.Program, predicate) -> List[ir.Variable]:
    return [v for v in program.global_block().vars.values() if predicate(v)]


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None, scope=None):
    """reference io.py:86 save_vars."""
    main_program = main_program or ir.default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(main_program, predicate or _is_persistable)
    os.makedirs(dirname, exist_ok=True)
    # ark crash safety: every file lands via tmp + os.replace, so a crash
    # mid-save leaves the previous version (or absence) of each file —
    # never a torn .npy/.npz that a later load half-reads
    if filename is not None:
        blob = {}
        for v in vars:
            arr = scope.find_var(v.name)
            if arr is None:
                raise RuntimeError(f"variable {v.name} not in scope")
            blob[v.name] = np.asarray(arr)
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path += ".npz"  # np.savez appends it for str paths; file
            # objects get written as-is, so match the legacy layout
        with atomic_file(path) as f:
            np.savez(f, **blob)
    else:
        for v in vars:
            arr = scope.find_var(v.name)
            if arr is None:
                raise RuntimeError(f"variable {v.name} not in scope")
            with atomic_file(os.path.join(dirname,
                                          v.name + PARAMS_SUFFIX)) as f:
                np.save(f, np.asarray(arr))


def save_params(executor, dirname, main_program=None, filename=None, scope=None):
    return save_vars(executor, dirname, main_program, None, _is_parameter,
                     filename, scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return save_vars(executor, dirname, main_program, None, _is_persistable,
                     filename, scope)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None, scope=None):
    """reference io.py:292 load_vars."""
    main_program = main_program or ir.default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = _collect(main_program, predicate or _is_persistable)
    if filename is not None:
        if not filename.endswith(".npz"):
            filename = filename + ".npz"  # np.savez appended it on save
        blob = np.load(os.path.join(dirname, filename))
        for v in vars:
            scope.set_var(v.name, np.asarray(blob[v.name]))
    else:
        for v in vars:
            path = os.path.join(dirname, v.name + PARAMS_SUFFIX)
            if not os.path.exists(path):
                raise RuntimeError(f"no saved file for variable {v.name} at {path}")
            scope.set_var(v.name, np.load(path))


def load_params(executor, dirname, main_program=None, filename=None, scope=None):
    return load_vars(executor, dirname, main_program, None, _is_parameter,
                     filename, scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return load_vars(executor, dirname, main_program, None, _is_persistable,
                     filename, scope)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, scope=None,
                         extra_programs=None, manifest_extra=None,
                         exclude_vars=None):
    """Prune to the inference slice and persist program+params
    (reference io.py:551).

    `extra_programs` ({filename: json-able meta dict}) lands additional
    program files in the same atomic dir — fluid-decode ships the
    decode-step program as `__decode__` next to the prefill `__model__`,
    committed (and sha256-manifested) as one unit. `manifest_extra` is
    merged into MANIFEST.json — the decode-step signature (max slots,
    block size, max context, cache var names) lives there so a registry
    load can size the KV cache and warm-compile the decode program
    without a probe request; loaders of legacy manifests see neither key.

    `exclude_vars` (a set of names) skips persistables whose VALUES must
    not land in the dir — fluid-fleet's distributed lookup tables, whose
    rows live only in pserver shards and are pulled at serve time
    (`fleet.sparse`). The program keeps the var (the lookup op needs its
    declared shape); a loader must feed or skip it — the manifest's
    `sparse` key (written by `fleet.sparse.save_sparse_inference_model`)
    tells `serve.ModelRegistry` which.

    ark crash safety: the whole model dir is STAGED in a same-parent tmp
    dir and swapped in at the end — program json and params commit as one
    unit, so a crash mid-save never leaves a torn dir mixing a new
    program with old params (or half the .npy files) that
    `load_inference_model` would half-load. The previous model dir, when
    one exists, survives any pre-swap crash."""
    main_program = main_program or ir.default_main_program()
    dirname = os.path.abspath(dirname)
    target_names = [v.name if isinstance(v, ir.Variable) else str(v)
                    for v in target_vars]
    pruned = main_program.clone(for_test=True)._prune(target_names)
    meta = {
        "program": pruned.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    parent = os.path.dirname(dirname) or "."
    os.makedirs(parent, exist_ok=True)
    base = os.path.basename(dirname)
    # sweep swap leftovers a CRASHED earlier save stranded — but only
    # old ones: a fresh .stage_/.old_ may belong to a concurrent saver
    # mid-swap, and deleting its stage (or its rollback copy) would turn
    # an overlapping save into data loss
    now = time.time()
    for name in os.listdir(parent):
        if name.startswith(f"{base}.old_") or \
                name.startswith(f".stage_{base}_"):
            p = os.path.join(parent, name)
            try:
                age = now - os.path.getmtime(p)
            except OSError:
                continue
            if age > 3600:
                shutil.rmtree(p, ignore_errors=True)
    # advisory serving lint: a fetch target nothing in the pruned slice
    # produces (and that isn't fed or persistable) fetches an undefined
    # value — almost always a target wired to the training-only graph
    from .analysis.diagnostics import lint_dead_fetch_targets
    for d in lint_dead_fetch_targets(pruned, target_names):
        logger.warning("save_inference_model: %s", d.format())
    stage = os.path.join(parent, f".stage_{base}_{uuid.uuid4().hex}")
    os.makedirs(stage)
    try:
        with open(os.path.join(stage, model_filename or MODEL_FILENAME),
                  "w") as f:
            json.dump(meta, f)
        for extra_name, extra_meta in (extra_programs or {}).items():
            with open(os.path.join(stage, extra_name), "w") as f:
                json.dump(extra_meta, f)
        excl = set(exclude_vars or ())
        if excl:
            save_vars(executor, stage, pruned,
                      predicate=lambda v: _is_persistable(v)
                      and v.name not in excl,
                      filename=params_filename, scope=scope)
        else:
            # the plain path keeps going through save_persistables — a
            # monkeypatchable seam crash-injection tests rely on
            save_persistables(executor, stage, pruned, params_filename,
                              scope)
        # integrity manifest, written LAST inside the stage: a sha256 per
        # payload file, so load_inference_model (and ark's
        # verify_checkpoint) can refuse a bit-rotted dir instead of
        # half-loading it. The dir swap below commits payloads + manifest
        # as one unit.
        files = {}
        for name in sorted(os.listdir(stage)):
            files[name] = {"sha256": file_sha256(os.path.join(stage, name)),
                           "bytes": os.path.getsize(
                               os.path.join(stage, name))}
        with atomic_file(os.path.join(stage, MODEL_MANIFEST), "w") as f:
            json.dump({"kind": "inference_model", "saved_at": time.time(),
                       "feed_names": list(feeded_var_names),
                       "fetch_names": target_names, "files": files,
                       **(manifest_extra or {})}, f, indent=1)
        if os.path.isdir(dirname):
            # swap: retire the old dir by rename (fast), bring the stage
            # in, then delete the retired copy. If the swap-in fails the
            # old dir is rolled back, so dirname is absent only across a
            # hard crash inside this window — never torn.
            old = dirname + f".old_{uuid.uuid4().hex}"
            os.rename(dirname, old)
            try:
                os.rename(stage, dirname)
            except BaseException:
                os.rename(old, dirname)   # roll the previous model back
                raise
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(stage, dirname)
    except BaseException:
        shutil.rmtree(stage, ignore_errors=True)
        raise
    return target_names


def verify_inference_model(dirname) -> Optional[dict]:
    """Check every file the model dir's MANIFEST.json names against its
    recorded sha256 (delegating to ark's verify_checkpoint — the two
    manifests share a schema by design). Returns the manifest dict, or
    None when the dir predates the manifest protocol (legacy dirs pass
    unverified — they have nothing to verify against). Raises
    ModelIntegrityError naming the first missing/corrupt file."""
    from .ark.checkpoint import CheckpointError, verify_checkpoint

    if not os.path.isfile(os.path.join(dirname, MODEL_MANIFEST)):
        logger.debug("model dir %s has no %s — legacy save, skipping "
                     "integrity verification", dirname, MODEL_MANIFEST)
        return None
    try:
        return verify_checkpoint(dirname)
    except CheckpointError as e:
        raise ModelIntegrityError(
            f"inference model dir fails integrity verification: {e}") from e
    except (OSError, json.JSONDecodeError) as e:
        raise ModelIntegrityError(
            f"model dir {dirname}: {MODEL_MANIFEST} is unreadable "
            f"({e}) — torn or corrupted save") from e


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None, verify=True,
                         skip_vars=None):
    """reference io.py:654 — returns (program, feed_names, fetch_vars).

    `verify=True` (default) checks the whole dir against the sha256
    MANIFEST.json the atomic `save_inference_model` wrote BEFORE
    deserializing anything: a bit-rotted or torn dir raises
    ModelIntegrityError naming the corrupt file instead of half-loading
    (program json parsed, some params garbage). Legacy dirs without a
    manifest load unverified.

    `skip_vars` names persistables the dir deliberately does NOT carry
    (saved with `exclude_vars=` — distributed lookup tables whose rows
    stay in pserver shards); they are neither loaded nor required, and
    the caller must feed them at run time."""
    if verify:
        verify_inference_model(dirname)
    with open(os.path.join(dirname, model_filename or MODEL_FILENAME)) as f:
        meta = json.load(f)
    program = ir.Program.from_dict(meta["program"])
    program._is_inference = True
    skip = set(skip_vars or ())
    load_vars(executor, dirname, program,
              vars=[v for v in _collect(program, _is_persistable)
                    if v.name not in skip],
              filename=params_filename, scope=scope)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def load_decode_program(dirname):
    """Load a generative model dir's decode-step program (saved via
    `extra_programs={DECODE_FILENAME: ...}`). Returns (program,
    feed_names, fetch_names) or None when the dir has no decode step —
    legacy one-shot model dirs load unchanged through
    `load_inference_model` and never reach here."""
    path = os.path.join(dirname, DECODE_FILENAME)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        meta = json.load(f)
    program = ir.Program.from_dict(meta["program"])
    program._is_inference = True
    return program, list(meta["feed_names"]), list(meta["fetch_names"])


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or ir.default_main_program()
    names = [v.name if isinstance(v, ir.Variable) else str(v) for v in target_vars]
    return main_program.clone(for_test=True)._prune(names)
