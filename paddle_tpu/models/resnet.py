"""ResNet for ImageNet / cifar10 (reference: benchmark/fluid/models/resnet.py).

The canonical topology is expressed through the layers DSL; every op lowers
to XLA and the whole step compiles into one fused TPU program (convs tile
onto the MXU).  ``data_format="NHWC"`` runs channels-last end-to-end, which
matches the TPU's native conv layout and avoids relayout transposes — use it
for training throughput; "NCHW" is kept for reference API parity.
"""

from __future__ import annotations

from .. import layers


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False, data_format="NCHW"):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False,
                         data_format=data_format)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def shortcut(input, ch_out, stride, is_test=False, data_format="NCHW"):
    c_axis = 1 if data_format == "NCHW" else len(input.shape) - 1
    if input.shape[c_axis] != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, data_format=data_format)
    return input


def basicblock(input, ch_out, stride, is_test=False, data_format="NCHW"):
    short = shortcut(input, ch_out, stride, is_test=is_test,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          data_format=data_format)
    return layers.elementwise_add(short, conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False, data_format="NCHW"):
    short = shortcut(input, ch_out * 4, stride, is_test=is_test,
                     data_format=data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, data_format=data_format)
    return layers.elementwise_add(short, conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_test=False,
               data_format="NCHW"):
    res_out = block_func(input, ch_out, stride, is_test=is_test,
                         data_format=data_format)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test=is_test,
                             data_format=data_format)
    return res_out


def _space_to_depth_stem(input, is_test, data_format):
    """TPU stem: 2x2 space-to-depth then a 3x3 conv on 12 channels.

    The canonical 7x7/s2 stem runs at ~1.7 TFLOP/s on the MXU because its
    3 input channels occupy 3 of 128 contraction lanes (measured on-chip;
    the deep layers hit 76-200 TFLOP/s). Folding a 2x2 pixel block into
    channels lifts the contraction to 12 lanes and makes the stem stride-1
    — the standard MLPerf-ResNet TPU transform. Output matches the
    canonical stem's [B, 112, 112, 64] geometry."""
    assert data_format == "NHWC", "space_to_depth stem is NHWC-only"
    H, W, C = input.shape[1], input.shape[2], input.shape[3]
    assert H % 2 == 0 and W % 2 == 0, \
        f"space_to_depth stem needs even spatial dims, got {H}x{W}"
    r = layers.reshape(input, [0, H // 2, 2, W // 2, 2, C])
    t = layers.transpose(r, perm=[0, 1, 3, 2, 4, 5])
    std = layers.reshape(t, [0, H // 2, W // 2, 4 * C])
    return conv_bn_layer(std, ch_out=64, filter_size=3, stride=1, padding=1,
                         is_test=is_test, data_format=data_format)


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False,
                    data_format="NCHW", stem="conv7"):
    cfg = {18: ([2, 2, 2, 1], basicblock),
           34: ([3, 4, 6, 3], basicblock),
           50: ([3, 4, 6, 3], bottleneck),
           101: ([3, 4, 23, 3], bottleneck),
           152: ([3, 8, 36, 3], bottleneck)}
    stages, block_func = cfg[depth]
    if stem == "space_to_depth":
        conv1 = _space_to_depth_stem(input, is_test, data_format)
    else:
        conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                              padding=3, is_test=is_test,
                              data_format=data_format)
    pool1 = layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                          pool_stride=2, pool_padding=1,
                          data_format=data_format)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_test=is_test,
                      data_format=data_format)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_test=is_test,
                      data_format=data_format)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_test=is_test,
                      data_format=data_format)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_test=is_test,
                      data_format=data_format)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                          global_pooling=True, data_format=data_format)
    out = layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False,
                   data_format="NCHW"):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1, padding=1,
                          is_test=is_test, data_format=data_format)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test=is_test,
                      data_format=data_format)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test=is_test,
                      data_format=data_format)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test=is_test,
                      data_format=data_format)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True, data_format=data_format)
    out = layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def build(class_dim=1000, depth=50, image_shape=(3, 224, 224), is_test=False,
          data_format="NCHW", stem="conv7"):
    if data_format == "NHWC" and image_shape[0] in (1, 3):
        image_shape = (image_shape[1], image_shape[2], image_shape[0])
    image = layers.data(name="image", shape=list(image_shape), dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = resnet_imagenet(image, class_dim=class_dim, depth=depth,
                              is_test=is_test, data_format=data_format,
                              stem=stem)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return {"image": image, "label": label}, {"loss": avg_cost, "acc": acc,
                                              "predict": predict}
