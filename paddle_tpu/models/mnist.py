"""MNIST CNN (reference: benchmark/fluid/models/mnist.py cnn_model)."""

from __future__ import annotations

from .. import layers, nets


def cnn_model(data):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2, pool_stride=2,
        act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    predict = layers.fc(input=conv_pool_2, size=10, act="softmax")
    return predict


def build(batch_size=None):
    """Returns (feeds, fetches): classification training graph."""
    images = layers.data(name="pixel", shape=[1, 28, 28], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = cnn_model(images)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return {"pixel": images, "label": label}, {"loss": avg_cost, "acc": acc,
                                               "predict": predict}
