"""DeepFM-style sparse CTR model (the reference's pserver sparse workload:
distributed lookup table design,
reference: doc/fluid/design/dist_train/distributed_lookup_table_design.md,
python/paddle/fluid/transpiler/distribute_transpiler.py:316 prefetch path).

TPU-native redesign: the giant embedding table is a dense sharded parameter
(ParamAttr.sharding rows over 'mp'); lookups become gathers and sparse grads
become scatter-adds that GSPMD turns into all-to-all + local updates — the
ICI replacement for pserver prefetch/push."""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


def build(num_fields=26, sparse_feature_dim=int(1e5), embedding_size=16,
          dense_dim=13, hidden_sizes=(400, 400, 400), distributed=False):
    """`distributed=True` marks the embedding tables is_distributed for the
    host parameter-server path (reference P5 distributed lookup table);
    default False uses the GSPMD 'mp' row sharding."""
    dense_input = layers.data(name="dense_input", shape=[dense_dim],
                              dtype="float32")
    sparse_input = layers.data(name="sparse_input", shape=[num_fields],
                               dtype="int64")
    label = layers.data(name="label", shape=[1], dtype="int64")

    # shared sharded embedding table: first-order (w) + second-order (v)
    sharding = None if distributed else ("mp", None)
    emb_v = layers.embedding(
        sparse_input, size=[sparse_feature_dim, embedding_size],
        is_distributed=distributed,
        param_attr=ParamAttr(name="fm_v", sharding=sharding))  # [B,F,K]
    emb_w = layers.embedding(
        sparse_input, size=[sparse_feature_dim, 1],
        is_distributed=distributed,
        param_attr=ParamAttr(name="fm_w", sharding=sharding))  # [B,F,1]

    # FM first order
    first_order = layers.reduce_sum(emb_w, dim=[1, 2], keep_dim=False)
    first_order = layers.reshape(first_order, shape=[-1, 1])

    # FM second order: 0.5 * ((sum v)^2 - sum v^2)
    sum_v = layers.reduce_sum(emb_v, dim=[1])             # [B,K]
    sum_v_sq = layers.elementwise_mul(sum_v, sum_v)
    v_sq = layers.elementwise_mul(emb_v, emb_v)
    sq_sum = layers.reduce_sum(v_sq, dim=[1])             # [B,K]
    second_order = layers.scale(
        layers.elementwise_sub(sum_v_sq, sq_sum), scale=0.5)
    second_order = layers.reduce_sum(second_order, dim=[1], keep_dim=True)

    # deep part
    deep = layers.reshape(emb_v, shape=[-1, num_fields * embedding_size])
    deep = layers.concat([deep, dense_input], axis=1)
    for h in hidden_sizes:
        deep = layers.fc(input=deep, size=h, act="relu")
    deep_out = layers.fc(input=deep, size=1, act=None)

    logit = layers.elementwise_add(
        layers.elementwise_add(first_order, second_order), deep_out)
    loss = layers.sigmoid_cross_entropy_with_logits(
        logit, layers.cast(label, "float32"))
    avg_loss = layers.mean(loss)
    predict = layers.sigmoid(logit)
    return ({"dense_input": dense_input, "sparse_input": sparse_input,
             "label": label},
            {"loss": avg_loss, "predict": predict})
