"""Attention seq2seq NMT (reference: benchmark/fluid/models/
machine_translation.py and the book test
python/paddle/fluid/tests/book/test_machine_translation.py).

Encoder: embedding -> fc -> masked-scan LSTM over the padded source.
Decoder (train): StaticRNN over target steps with dot-product attention into
the encoder states (the reference used ConvexCombination/attention via
sequence_expand + sequence_softmax on LoD; here attention is a masked
softmax over the padded time axis).
Decoder (infer): fixed-length scan + static-beam `beam_search_step` /
`beam_backtrack` ops (ops/beam.py) replacing the reference's LoD beam ops.
"""

from __future__ import annotations

from .. import layers
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr


def encoder(src_word, dict_size, emb_dim, hidden_dim):
    emb = layers.embedding(src_word, size=[dict_size, emb_dim],
                           param_attr=ParamAttr(name="src_emb"))
    proj = layers.fc(input=emb, size=hidden_dim * 4, num_flatten_dims=2,
                     bias_attr=False, param_attr=ParamAttr(name="enc_proj"))
    hidden, _ = layers.dynamic_lstm(input=proj, size=hidden_dim * 4,
                                    param_attr=ParamAttr(name="enc_lstm_w"))
    return hidden  # [B, Ts, H], carries @SEQLEN of src


def _attention(dec_h, enc_out):
    """dot attention: dec_h [N, H], enc_out [N, Ts, H] -> context [N, H].
    The softmax is masked by enc_out's @SEQLEN companion (LoD analog)."""
    scores = layers.matmul(enc_out, layers.unsqueeze(dec_h, axes=[2]))
    scores = layers.squeeze(scores, axes=[2])          # [N, Ts]
    scores.lod_level = enc_out.lod_level
    weights = layers.sequence_softmax(scores)           # masked by @SEQLEN
    ctx = layers.matmul(layers.unsqueeze(weights, axes=[1]), enc_out)
    return layers.squeeze(ctx, axes=[1])                # [N, H]


def train_decoder(enc_out, trg_word, dict_size, emb_dim, hidden_dim):
    trg_emb = layers.embedding(trg_word, size=[dict_size, emb_dim],
                               param_attr=ParamAttr(name="trg_emb"))
    h0 = layers.fill_constant_batch_size_like(enc_out, [-1, hidden_dim],
                                              "float32", 0.0)
    rnn = layers.StaticRNN(name="dec_rnn")
    with rnn.step():
        emb_t = rnn.step_input(trg_emb)                 # [B, E]
        h = rnn.memory(init=h0)                          # [B, H]
        ctx = _attention(h, enc_out)
        gate_in = layers.fc(input=layers.concat([emb_t, ctx], axis=1),
                            size=hidden_dim * 3, bias_attr=False,
                            param_attr=ParamAttr(name="dec_gate_proj"))
        nh, _, _ = layers.gru_unit(gate_in, h, hidden_dim * 3,
                                   param_attr=ParamAttr(name="dec_gru_w"))
        rnn.update_memory(h, nh)
        out = layers.fc(input=nh, size=dict_size, act=None,
                        param_attr=ParamAttr(name="dec_out_w"))
        rnn.step_output(out)
    return rnn()                                        # [B, Tt, V]


def build(dict_size=10000, emb_dim=256, hidden_dim=256):
    """Teacher-forced training graph. Feeds: src_word [B,Ts,1] (lod),
    trg_word [B,Tt,1], lbl_word [B,Tt,1]."""
    src = layers.data(name="src_word", shape=[1], dtype="int64", lod_level=1)
    trg = layers.data(name="trg_word", shape=[-1, -1, 1], dtype="int64",
                      append_batch_size=False)
    lbl = layers.data(name="lbl_word", shape=[-1, -1, 1], dtype="int64",
                      append_batch_size=False)
    enc_out = encoder(src, dict_size, emb_dim, hidden_dim)
    logits = train_decoder(enc_out, trg, dict_size, emb_dim, hidden_dim)
    loss = layers.softmax_with_cross_entropy(
        logits=logits, label=layers.squeeze(lbl, axes=[2]))
    avg_loss = layers.mean(loss)
    return ({"src_word": src, "trg_word": trg, "lbl_word": lbl},
            {"loss": avg_loss, "logits": logits})


def build_infer(dict_size=10000, emb_dim=256, hidden_dim=256, beam_size=4,
                max_len=16, start_id=0, end_id=1):
    """Beam-search decode graph. Feed src_word; fetch translation ids+scores."""
    src = layers.data(name="src_word", shape=[1], dtype="int64", lod_level=1)
    enc_out = encoder(src, dict_size, emb_dim, hidden_dim)   # [B, Ts, H]

    # tile encoder states across beams: [B, Ts, H] -> [B*K, Ts, H]
    enc_tiled = tile_beam(enc_out, beam_size)

    ids0 = layers.fill_constant_batch_size_like(enc_out, [-1, beam_size],
                                                "int32", float(start_id))
    fin0 = layers.cast(layers.fill_constant_batch_size_like(
        enc_out, [-1, beam_size], "int32", 0.0), "bool")
    # only beam 0 live at step 0 so beams don't start as duplicates
    mask0 = layers.fill_constant_batch_size_like(enc_out, [-1, beam_size],
                                                 "float32", 0.0)
    import numpy as np
    first_active = layers.assign(
        np.array([0.0] + [-1e9] * (beam_size - 1), np.float32))
    scores0 = layers.elementwise_add(mask0, first_active, axis=-1)
    h0 = tile_beam(layers.fill_constant_batch_size_like(
        enc_out, [-1, hidden_dim], "float32", 0.0), beam_size)

    rnn = layers.StaticRNN(name="beam_rnn", num_steps=max_len)
    with rnn.step():
        ids = rnn.memory(init=ids0)          # [B, K] int32
        scores = rnn.memory(init=scores0)    # [B, K]
        fin = rnn.memory(init=fin0)          # [B, K] bool
        h = rnn.memory(init=h0)              # [B*K, H]

        flat_ids = layers.reshape(ids, shape=[-1, 1])
        emb_t = layers.embedding(layers.cast(flat_ids, "int64"),
                                 size=[dict_size, emb_dim],
                                 param_attr=ParamAttr(name="trg_emb"))
        emb_t = layers.squeeze(emb_t, axes=[1]) if len(emb_t.shape) == 3 \
            else emb_t
        ctx = _attention(h, enc_tiled)
        gate_in = layers.fc(input=layers.concat([emb_t, ctx], axis=1),
                            size=hidden_dim * 3, bias_attr=False,
                            param_attr=ParamAttr(name="dec_gate_proj"))
        nh, _, _ = layers.gru_unit(gate_in, h, hidden_dim * 3,
                                   param_attr=ParamAttr(name="dec_gru_w"))
        logits = layers.fc(input=nh, size=dict_size, act=None,
                           param_attr=ParamAttr(name="dec_out_w"))
        logp = _log_softmax(logits)
        logp3 = layers.reshape(logp, shape=[-1, beam_size, dict_size])
        new_ids, parents, new_scores, new_fin = beam_search_step(
            logp3, scores, fin, beam_size=beam_size, end_id=end_id)
        # reorder decoder state by parent beam
        h3 = layers.reshape(nh, shape=[-1, beam_size, hidden_dim])
        h_sel = batch_gather(h3, parents)
        rnn.update_memory(ids, new_ids)
        rnn.update_memory(scores, new_scores)
        rnn.update_memory(fin, new_fin)
        rnn.update_memory(h, layers.reshape(h_sel, shape=[-1, hidden_dim]))
        rnn.step_output(new_ids)
        rnn.step_output(parents)
        rnn.step_output(new_scores)

    ids_hist, parents_hist, scores_hist = rnn()   # each [B, T, K]
    final_scores = layers.squeeze(
        layers.slice(scores_hist, axes=[1], starts=[max_len - 1],
                     ends=[max_len]), axes=[1])
    seq_ids, seq_scores = beam_backtrack(ids_hist, parents_hist, final_scores)
    return {"src_word": src}, {"ids": seq_ids, "scores": seq_scores}


# -- thin op wrappers --------------------------------------------------------

def _log_softmax(x):
    helper = LayerHelper("log_softmax")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("log_softmax", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": -1})
    return out


def beam_search_step(logprobs, acc_scores, finished, beam_size, end_id=1):
    helper = LayerHelper("beam_search_step")
    ids = helper.create_variable_for_type_inference(dtype="int32")
    parents = helper.create_variable_for_type_inference(dtype="int32")
    scores = helper.create_variable_for_type_inference(dtype="float32")
    fin = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("beam_search_step",
                     inputs={"LogProbs": [logprobs.name],
                             "AccScores": [acc_scores.name],
                             "Finished": [finished.name]},
                     outputs={"Ids": [ids.name], "Parents": [parents.name],
                              "AccScoresOut": [scores.name],
                              "FinishedOut": [fin.name]},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    return ids, parents, scores, fin


def beam_backtrack(ids_hist, parents_hist, final_scores):
    helper = LayerHelper("beam_backtrack")
    seq = helper.create_variable_for_type_inference(dtype="int32")
    scores = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("beam_backtrack",
                     inputs={"Ids": [ids_hist.name],
                             "Parents": [parents_hist.name],
                             "AccScores": [final_scores.name]},
                     outputs={"SentenceIds": [seq.name],
                              "SentenceScores": [scores.name]})
    return seq, scores


def tile_beam(x, beam_size):
    helper = LayerHelper("tile_beam")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("tile_beam", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"beam_size": beam_size})
    out.lod_level = x.lod_level
    return out


def batch_gather(x, index):
    helper = LayerHelper("batch_gather")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("batch_gather",
                     inputs={"X": [x.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out
