"""SE-ResNeXt-50/101/152 for ImageNet (BASELINE.json config
"ResNet-50 / SE-ResNeXt-50 ImageNet"; topology per the SE-ResNeXt paper
family the reference's model zoo shipped alongside benchmark/fluid —
grouped 3x3 bottlenecks, cardinality 32, squeeze-excitation with
reduction 16).

Like models/resnet.py everything is layers-DSL; grouped convs lower to
one XLA convolution with feature_group_count (MXU-tiled), and the SE
block's global pool + two fcs fuse into the surrounding program.
"""

from __future__ import annotations

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False, data_format="NCHW"):
    conv = layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         act=None, bias_attr=False, data_format=data_format)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def squeeze_excitation(input, num_channels, reduction_ratio=16,
                       data_format="NCHW"):
    pool = layers.pool2d(input=input, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    return _scale_channels(input, excitation, data_format)


def _scale_channels(x, gate, data_format):
    """x [B,C,H,W] (or NHWC) * gate [B,C] broadcast over space."""
    shape = [0, -1, 1, 1] if data_format == "NCHW" else [0, 1, 1, -1]
    gate = layers.reshape(gate, shape=shape)
    return layers.elementwise_mul(x, gate)


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False, data_format="NCHW"):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_test=is_test, data_format=data_format)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test, data_format=data_format)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio,
                               data_format)
    c_axis = 1 if data_format == "NCHW" else len(input.shape) - 1
    if input.shape[c_axis] != num_filters * 2 or stride != 1:
        short = conv_bn_layer(input, num_filters * 2, 1, stride=stride,
                              act=None, is_test=is_test,
                              data_format=data_format)
    else:
        short = input
    return layers.elementwise_add(short, scale, act="relu")


def se_resnext_imagenet(input, class_dim=1000, depth=50, cardinality=32,
                        reduction_ratio=16, is_test=False,
                        data_format="NCHW"):
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
    assert depth in cfg, f"SE-ResNeXt depth must be one of {sorted(cfg)}"
    layers_per_stage = cfg[depth]
    num_filters = [128, 256, 512, 1024]

    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu",
                         is_test=is_test, data_format=data_format)
    conv = layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type="max",
                         data_format=data_format)
    for stage, count in enumerate(layers_per_stage):
        for i in range(count):
            conv = bottleneck_block(
                conv, num_filters[stage], stride=2 if i == 0 and stage > 0
                else 1, cardinality=cardinality,
                reduction_ratio=reduction_ratio, is_test=is_test,
                data_format=data_format)
    pool = layers.pool2d(input=conv, pool_type="avg", global_pooling=True,
                         data_format=data_format)
    drop = layers.dropout(pool, dropout_prob=0.5, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def build(class_dim=1000, depth=50, image_shape=(3, 224, 224),
          is_test=False, data_format="NCHW"):
    if data_format == "NHWC" and image_shape[0] in (1, 3):
        image_shape = (image_shape[1], image_shape[2], image_shape[0])
    image = layers.data(name="image", shape=list(image_shape),
                        dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    predict = se_resnext_imagenet(image, class_dim=class_dim, depth=depth,
                                  is_test=is_test, data_format=data_format)
    cost = layers.cross_entropy(input=predict, label=label)
    loss = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return ({"image": image, "label": label},
            {"loss": loss, "accuracy": acc, "predict": predict})
