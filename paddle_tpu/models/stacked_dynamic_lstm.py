"""Stacked LSTM text classifier over variable-length sequences
(reference: benchmark/fluid/models/stacked_dynamic_lstm.py).

The reference runs dynamic (LoD) LSTMs over unpadded batches; the TPU design
runs masked `lax.scan` LSTMs over padded batches + @SEQLEN lengths — same
numerics on the valid prefix."""

from __future__ import annotations

from .. import layers


def build(dict_size=30000, emb_dim=512, hidden_dim=512, stacked_num=3,
          class_num=2):
    words = layers.data(name="words", shape=[1], dtype="int64", lod_level=1)
    label = layers.data(name="label", shape=[1], dtype="int64")
    emb = layers.embedding(input=words, size=[dict_size, emb_dim])

    inp = emb
    for _ in range(stacked_num):
        proj = layers.fc(input=inp, size=hidden_dim * 4, act=None,
                         num_flatten_dims=2)
        hidden, cell = layers.dynamic_lstm(input=proj, size=hidden_dim * 4)
        inp = hidden

    last = layers.sequence_pool(input=inp, pool_type="max")
    logit = layers.fc(input=last, size=class_num, act="softmax")
    loss = layers.cross_entropy(input=logit, label=label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(input=logit, label=label)
    return {"words": words, "label": label}, {"loss": avg_loss, "acc": acc}
