"""Transformer-base for WMT En-De (the BASELINE.json headline seq workload).

The reference's NMT config is an attention seq2seq
(reference: benchmark/fluid/models/machine_translation.py); its only
attention primitive is nets.scaled_dot_product_attention
(reference: python/paddle/fluid/nets.py:329). This model composes that same
DSL into the standard Transformer encoder-decoder — built entirely from
framework layers, so the whole training step is one XLA program where every
matmul maps to the MXU.

TP-ready: q/k/v/ffn weights carry ParamAttr.sharding annotations consumed by
the parallel transpiler ('mp' axis), giving Megatron-style tensor parallelism
through GSPMD.
"""

from __future__ import annotations

from .. import layers
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .. import initializer as init


def _shard(spec):
    return ParamAttr(sharding=spec)


def _causal_mask(size):
    helper = LayerHelper("causal_mask")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("causal_mask", outputs={"Out": [out.name]},
                     attrs={"size": size, "neg": -1e9})
    return out


def _pos_table(size, d_model):
    helper = LayerHelper("pos_encoding")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("sinusoid_pos_encoding", outputs={"Out": [out.name]},
                     attrs={"size": size, "d_model": d_model})
    return out


def _fused_attention(qh, kh, vh, d_head, causal, dropout_rate, is_test):
    """Flash-attention op: one O(T)-memory Pallas kernel instead of the
    matmul/softmax/dropout/matmul chain (in-kernel weight dropout)."""
    helper = LayerHelper("fused_attention")
    out = helper.create_variable_for_type_inference(dtype=qh.dtype)
    helper.append_op("fused_attention",
                     inputs={"Q": [qh.name], "K": [kh.name], "V": [vh.name]},
                     outputs={"Out": [out.name]},
                     attrs={"causal": causal, "sm_scale": d_head ** -0.5,
                            "dropout_rate": dropout_rate, "is_test": is_test})
    return out


def multi_head_attention(q_in, kv_in, d_model, num_heads, dropout_rate=0.0,
                         causal=False, is_test=False, name="", fused=True):
    d_head = d_model // num_heads
    q = layers.fc(input=q_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_shard((None, "mp")), name=name + "_q")
    k = layers.fc(input=kv_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_shard((None, "mp")), name=name + "_k")
    v = layers.fc(input=kv_in, size=d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_shard((None, "mp")), name=name + "_v")

    def split_heads(x):
        r = layers.reshape(x, shape=[0, 0, num_heads, d_head])
        return layers.transpose(r, perm=[0, 2, 1, 3])

    qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
    if fused:
        ctx = _fused_attention(qh, kh, vh, d_head, causal, dropout_rate,
                               is_test)
    else:
        scores = layers.matmul(qh, kh, transpose_y=True, alpha=d_head ** -0.5)
        if causal:
            mask_var = _causal_mask(scores.shape[-1])
            scores = layers.elementwise_add(scores, mask_var)
        weights = layers.softmax(scores)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                     is_test=is_test,
                                     dropout_implementation="upscale_in_train")
        ctx = layers.matmul(weights, vh)
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    merged = layers.reshape(ctx, shape=[0, 0, d_model])
    return layers.fc(input=merged, size=d_model, num_flatten_dims=2,
                     bias_attr=False, param_attr=_shard(("mp", None)),
                     name=name + "_o")


def ffn(x, d_model, d_inner, dropout_rate=0.0, is_test=False, name=""):
    h = layers.fc(input=x, size=d_inner, num_flatten_dims=2, act="relu",
                  param_attr=_shard((None, "mp")), name=name + "_ffn1")
    if dropout_rate:
        h = layers.dropout(h, dropout_prob=dropout_rate, is_test=is_test,
                           dropout_implementation="upscale_in_train")
    return layers.fc(input=h, size=d_model, num_flatten_dims=2,
                     param_attr=_shard(("mp", None)), name=name + "_ffn2")


def _add_norm(x, sub, dropout_rate=0.0, is_test=False):
    if dropout_rate:
        sub = layers.dropout(sub, dropout_prob=dropout_rate, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return layers.layer_norm(layers.elementwise_add(x, sub), begin_norm_axis=2)


def _embed(ids, vocab_size, d_model, seq_len, dropout_rate, is_test, name):
    emb = layers.embedding(ids, size=[vocab_size, d_model],
                           param_attr=ParamAttr(
                               name=name, sharding=("mp", None),
                               initializer=init.NormalInitializer(0.0, d_model ** -0.5)))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    pos = _pos_table(seq_len, d_model)
    out = layers.elementwise_add(emb, pos, axis=-1)
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate, is_test=is_test,
                             dropout_implementation="upscale_in_train")
    return out


def transformer(src_vocab_size=30000, trg_vocab_size=30000, seq_len=256,
                n_layer=6, n_head=8, d_model=512, d_inner=2048,
                dropout_rate=0.1, is_test=False, label_smooth_eps=0.0,
                fused_attention=True):
    """Returns (feeds, fetches) for a teacher-forced training step.
    Sequences are bucketed/padded to the static `seq_len` (TPU-friendly
    static shapes; the reference padded per-batch via LoD)."""
    src = layers.data(name="src_word", shape=[-1, seq_len], dtype="int64",
                      append_batch_size=False)
    trg = layers.data(name="trg_word", shape=[-1, seq_len], dtype="int64",
                      append_batch_size=False)
    lbl = layers.data(name="lbl_word", shape=[-1, seq_len], dtype="int64",
                      append_batch_size=False)

    enc = _embed(src, src_vocab_size, d_model, seq_len, dropout_rate,
                 is_test, "src_emb")
    for i in range(n_layer):
        attn = multi_head_attention(enc, enc, d_model, n_head, dropout_rate,
                                    is_test=is_test, name=f"enc{i}_self",
                                    fused=fused_attention)
        enc = _add_norm(enc, attn, dropout_rate, is_test)
        f = ffn(enc, d_model, d_inner, dropout_rate, is_test, name=f"enc{i}")
        enc = _add_norm(enc, f, dropout_rate, is_test)

    dec = _embed(trg, trg_vocab_size, d_model, seq_len, dropout_rate,
                 is_test, "trg_emb")
    for i in range(n_layer):
        self_attn = multi_head_attention(dec, dec, d_model, n_head,
                                         dropout_rate, causal=True,
                                         is_test=is_test, name=f"dec{i}_self",
                                         fused=fused_attention)
        dec = _add_norm(dec, self_attn, dropout_rate, is_test)
        cross = multi_head_attention(dec, enc, d_model, n_head, dropout_rate,
                                     is_test=is_test, name=f"dec{i}_cross",
                                     fused=fused_attention)
        dec = _add_norm(dec, cross, dropout_rate, is_test)
        f = ffn(dec, d_model, d_inner, dropout_rate, is_test, name=f"dec{i}")
        dec = _add_norm(dec, f, dropout_rate, is_test)

    logits = layers.fc(input=dec, size=trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_shard((None, "mp")),
                       name="out_proj")
    loss = layers.softmax_with_cross_entropy(logits=logits, label=lbl)
    avg_loss = layers.mean(loss)
    return ({"src_word": src, "trg_word": trg, "lbl_word": lbl},
            {"loss": avg_loss, "logits": logits})


def build(**kw):
    return transformer(**kw)
