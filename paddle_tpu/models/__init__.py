"""Model zoo mirroring the reference benchmark configs
(reference: benchmark/fluid/models/{mnist,resnet,vgg,stacked_dynamic_lstm,
machine_translation}.py) plus Transformer-base and DeepFM (the BASELINE.json
target workloads)."""

from . import mnist  # noqa: F401
from . import resnet  # noqa: F401
from . import vgg  # noqa: F401
from . import stacked_dynamic_lstm  # noqa: F401
from . import transformer  # noqa: F401
from . import deepfm  # noqa: F401
from . import machine_translation  # noqa: F401
from . import se_resnext  # noqa: F401
from . import tiny_lm  # noqa: F401
