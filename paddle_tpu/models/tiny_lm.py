"""Tiny autoregressive decoder LM: the fluid-decode reference model.

Small enough to compile in seconds on the CPU test backend, but built
exactly like a production decode path: a PREFILL program (prompt at a
bucket-ladder rung -> causal attention -> K/V scattered into the paged
cache -> next-token logits at each row's last valid position) and a
DECODE program (one token per fixed slot -> K/V appended at seq_len-1 ->
ragged paged attention over the block table -> logits), sharing one
parameter set and one per-layer ``*@KV_CACHE`` cache (ops/
paged_attention.py). Both programs are saved into ONE atomic model dir
(`save_tiny_lm`): prefill as `__model__`, decode as `__decode__`, and
the decode-step signature in MANIFEST.json so `serve.ModelRegistry` can
size the cache and warm-compile the decode step without a probe request.

Architecture per layer: pre-norm-free residual attention + 2x relu MLP
(no positional embedding — causality alone orders the tiny vocab
sequences, and fewer moving parts keeps the paged-vs-dense bit-identity
pins sharp). Sampling is greedy argmax on the host, so generations are
deterministic and the continuous-batching-equals-solo-run tests can
compare token-for-token.
"""

from __future__ import annotations

import math
from typing import Dict

import numpy as np

from .. import initializer as init
from ..core import ir
from ..layer_helper import LayerHelper
from ..layers import nn as layers_nn
from ..layers.io import data as data_layer
from ..param_attr import ParamAttr

DTYPE = "float32"


def _param(name: str, shape, std: float):
    helper = LayerHelper("tiny_lm")
    return helper.create_parameter(
        ParamAttr(name=name,
                  initializer=init.NormalInitializer(0.0, std)),
        list(shape), DTYPE)


def _add(x, y):
    helper = LayerHelper("tiny_lm")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("elementwise_add", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": -1})
    return out


def default_signature(vocab=32, d_model=16, n_heads=2, n_layers=2,
                      max_slots=4, block_size=4, max_context=32,
                      num_blocks=None, prefill_rows=(1, 2, 4),
                      prefill_seq_rungs=(8, 16), eos_token=None,
                      kv_dtype="fp32") -> Dict:
    """The decode-step signature recorded in MANIFEST.json — everything
    a registry needs to materialize the cache and warm both programs.

    `kv_dtype="int8"` switches the cache residency to fluid-torrent's
    int8-quantized layout: int8 cache arrays plus a per-block float32
    scale var per cache var (`scale_vars` maps cache var -> scale var)
    and one shared [1] int32 requant-event counter (`requant_var`) the
    serve engine meters."""
    max_bps = -(-max_context // block_size)
    if num_blocks is None:
        # worst case: every slot at max context, plus the trash block
        num_blocks = 1 + max_slots * max_bps
    if kv_dtype not in ("fp32", "int8"):
        raise ValueError(f"kv_dtype must be 'fp32' or 'int8', "
                         f"got {kv_dtype!r}")
    cache_vars = [f"lm_kv_{kv}_{i}{ir.KV_CACHE_SUFFIX}"
                  for i in range(n_layers) for kv in ("k", "v")]
    sig = {
        "vocab": int(vocab), "d_model": int(d_model),
        "num_heads": int(n_heads), "head_dim": int(d_model // n_heads),
        "n_layers": int(n_layers), "max_slots": int(max_slots),
        "block_size": int(block_size), "max_context": int(max_context),
        "max_blocks_per_seq": int(max_bps), "num_blocks": int(num_blocks),
        "prefill_rows": [int(r) for r in prefill_rows],
        "prefill_seq_rungs": [int(r) for r in prefill_seq_rungs],
        "eos_token": eos_token,
        "cache_vars": cache_vars,
        "decode_feeds": ["tokens", "block_tables", "seq_lens"],
        "kv_dtype": str(kv_dtype),
    }
    if kv_dtype == "int8":
        sig["scale_vars"] = {c: _scale_var_name(c) for c in cache_vars}
        sig["requant_var"] = f"lm_kv_requant{ir.KV_CACHE_SUFFIX}"
    return sig


def _scale_var_name(cache_var: str) -> str:
    """Per-block scale var of an int8 cache var — keeps the @KV_CACHE
    suffix so io._is_persistable skips it from serialization exactly
    like the cache arrays (the registry materializes zeros)."""
    base = cache_var[: -len(ir.KV_CACHE_SUFFIX)] \
        if cache_var.endswith(ir.KV_CACHE_SUFFIX) else cache_var
    return f"{base}_scale{ir.KV_CACHE_SUFFIX}"


def _cache_vars(block, sig, layer: int):
    shape = (sig["num_blocks"], sig["block_size"], sig["num_heads"],
             sig["head_dim"])
    dtype = "int8" if sig.get("kv_dtype") == "int8" else DTYPE
    out = []
    for kv in ("k", "v"):
        name = f"lm_kv_{kv}_{layer}{ir.KV_CACHE_SUFFIX}"
        if name in block.vars:
            out.append(block.vars[name])
        else:
            out.append(block.create_var(name=name, shape=shape, dtype=dtype,
                                        persistable=True,
                                        stop_gradient=True))
    return out


def _q8_side_vars(block, sig, kc, vc):
    """The int8 layout's sidecar vars: per-block scales for this layer's
    K and V caches plus the shared requant counter."""
    out = []
    for cache in (kc, vc):
        name = sig["scale_vars"][cache.name]
        if name in block.vars:
            out.append(block.vars[name])
        else:
            out.append(block.create_var(
                name=name, shape=(sig["num_blocks"],), dtype=DTYPE,
                persistable=True, stop_gradient=True))
    rq = sig["requant_var"]
    if rq in block.vars:
        out.append(block.vars[rq])
    else:
        out.append(block.create_var(name=rq, shape=(1,), dtype="int32",
                                    persistable=True, stop_gradient=True))
    return out


def _body(tokens, block_tables, seq_lens, sig, phase: str):
    """Shared trunk: embedding -> n_layers of (attention + MLP) ->
    logits. `phase` picks the attention op ("prefill_attention" on
    [rows, T, D] with gather_last_token at the end, "paged_attention" on
    [slots, D])."""
    import paddle_tpu as fluid

    block = fluid.default_main_program().global_block()
    d, H = sig["d_model"], sig["num_heads"]
    std = 0.5 / math.sqrt(d)
    emb = _param("lm_emb", (sig["vocab"], d), std)
    helper = LayerHelper("tiny_lm")
    h = helper.create_variable_for_type_inference(DTYPE)
    helper.append_op("lookup_table",
                     inputs={"W": [emb.name], "Ids": [tokens.name]},
                     outputs={"Out": [h.name]},
                     attrs={"padding_idx": -1, "is_sparse": False,
                            "is_distributed": False})
    sm_scale = 1.0 / math.sqrt(sig["head_dim"])
    q8 = sig.get("kv_dtype") == "int8"
    for i in range(sig["n_layers"]):
        kc, vc = _cache_vars(block, sig, i)
        q = layers_nn.matmul(h, _param(f"lm_l{i}_wq", (d, d), std))
        k = layers_nn.matmul(h, _param(f"lm_l{i}_wk", (d, d), std))
        v = layers_nn.matmul(h, _param(f"lm_l{i}_wv", (d, d), std))
        attn = helper.create_variable_for_type_inference(DTYPE)
        op_type = ("prefill_attention" if phase == "prefill"
                   else "paged_attention") + ("_q8" if q8 else "")
        inputs = {"Q": [q.name], "K": [k.name], "V": [v.name],
                  "KCache": [kc.name], "VCache": [vc.name],
                  "BlockTables": [block_tables.name],
                  "SeqLens": [seq_lens.name]}
        outputs = {"Out": [attn.name], "KCacheOut": [kc.name],
                   "VCacheOut": [vc.name]}
        if q8:
            ks, vs, rq = _q8_side_vars(block, sig, kc, vc)
            inputs.update({"KScale": [ks.name], "VScale": [vs.name]})
            outputs.update({"KScaleOut": [ks.name],
                            "VScaleOut": [vs.name]})
            if phase != "prefill":
                inputs["RequantCount"] = [rq.name]
                outputs["RequantCountOut"] = [rq.name]
        helper.append_op(
            op_type, inputs=inputs, outputs=outputs,
            attrs={"num_heads": H, "sm_scale": sm_scale})
        h = _add(h, layers_nn.matmul(
            attn, _param(f"lm_l{i}_wo", (d, d), std)))
        m = layers_nn.relu(layers_nn.matmul(
            h, _param(f"lm_l{i}_w1", (d, 2 * d), std)))
        h = _add(h, layers_nn.matmul(
            m, _param(f"lm_l{i}_w2", (2 * d, d), std)))
    if phase == "prefill":
        last = helper.create_variable_for_type_inference(DTYPE)
        helper.append_op("gather_last_token",
                         inputs={"X": [h.name], "SeqLens": [seq_lens.name]},
                         outputs={"Out": [last.name]})
        h = last
    return layers_nn.matmul(h, _param("lm_head", (d, sig["vocab"]), std))


def build_tiny_lm(sig=None, seed=11, **sig_kwargs):
    """Build (prefill_program, decode_program, startup_program, logits
    pair, signature). Both main programs share parameters by explicit
    name; the startup program initializes each exactly once."""
    import paddle_tpu as fluid

    sig = dict(sig) if sig else default_signature(**sig_kwargs)
    startup = fluid.Program()
    prefill = fluid.Program()
    max_b = sig["max_blocks_per_seq"]
    with fluid.program_guard(prefill, startup), fluid.unique_name.guard():
        tokens = data_layer("tokens", shape=[-1], dtype="int64")
        bt = data_layer("block_tables", shape=[max_b], dtype="int32")
        sl = data_layer("seq_lens", shape=[-1], dtype="int32",
                        append_batch_size=False)
        prefill_logits = _body(tokens, bt, sl, sig, "prefill")
    decode = fluid.Program()
    with fluid.program_guard(decode, startup), fluid.unique_name.guard():
        tokens = data_layer("tokens", shape=[1], dtype="int64")
        bt = data_layer("block_tables", shape=[max_b], dtype="int32")
        sl = data_layer("seq_lens", shape=[-1], dtype="int32",
                        append_batch_size=False)
        decode_logits = _body(tokens, bt, sl, sig, "decode")
    prefill.random_seed = decode.random_seed = startup.random_seed = seed
    return prefill, decode, startup, (prefill_logits, decode_logits), sig


def save_tiny_lm(dirname, sig=None, seed=11, scale=1.0, **sig_kwargs):
    """Init + save a tiny LM as a generative model dir (atomic commit:
    prefill `__model__` + decode `__decode__` + params + MANIFEST with
    the decode signature). `scale` perturbs the params so a re-save is an
    observably different version (hot-swap drills). Returns the
    signature."""
    import paddle_tpu as fluid
    from .. import io as _io

    prefill, decode_prog, startup, (p_logits, d_logits), sig = \
        build_tiny_lm(sig=sig, seed=seed, **sig_kwargs)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    if scale != 1.0:
        for name in list(scope.local_var_names()):
            if name.startswith("lm_"):
                scope.set_var(name, np.asarray(scope.find_var(name)) * scale)
    decode_meta = {
        "program": decode_prog.to_dict(),
        "feed_names": list(sig["decode_feeds"]),
        "fetch_names": [d_logits.name],
    }
    _io.save_inference_model(
        dirname, ["tokens", "block_tables", "seq_lens"], [p_logits], exe,
        main_program=prefill, scope=scope,
        extra_programs={_io.DECODE_FILENAME: decode_meta},
        manifest_extra={"decode": sig})
    return sig
