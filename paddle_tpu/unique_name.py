"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""

from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, prefix: str) -> str:
        i = self.ids[prefix]
        self.ids[prefix] += 1
        return f"{prefix}_{i}"


_generator = UniqueNameGenerator()


def generate(prefix: str) -> str:
    return _generator(prefix)


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope the counter (reference unique_name.guard) so separate programs
    can reuse parameter names deterministically."""
    global _generator
    prev = _generator
    _generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        _generator = prev


def switch(new_generator=None):
    """Swap the global generator, returning the old one (reference
    unique_name.switch — guard() is built on it there)."""
    global _generator
    prev = _generator
    _generator = new_generator or UniqueNameGenerator()
    return prev
