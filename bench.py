#!/usr/bin/env python
"""Headline benchmarks on one TPU chip, printed as ONE JSON line.

Primary metric: ResNet-50 ImageNet training throughput (NHWC, bf16 AMP).
Baseline: the best ResNet-50 training number published in the reference repo —
84.08 images/sec (CPU MKL-DNN bs256, reference
benchmark/IntelOptimizedPaddle.md:41-45; no GPU ResNet-50 number is published
in-tree, see BASELINE.md).

`extra` carries the second BASELINE.json metric (Transformer-base WMT
tokens/sec, seq 256) and a long-context Transformer run (seq 2048) through
the Pallas flash-attention path.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_IMG_PER_SEC = 84.08


def _sync(x):
    # axon's block_until_ready is a no-op; force with a host transfer
    np.asarray(x)


def bench_resnet(fluid, models, jax):
    batch_size = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        feeds, fetches = models.resnet.build(class_dim=1000, depth=50,
                                             data_format="NHWC")
        loss = fetches["loss"]
        opt = fluid.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        opt.minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0),
                         amp=os.environ.get("BENCH_AMP", "1") == "1")
    exe.run(startup, scope=scope)

    # Pre-stage batches on device and cycle them — the AsyncFeeder
    # double-buffer pattern. (This dev environment reaches the chip through a
    # ~40 MB/s tunnel; production hosts overlap H2D with compute, which
    # AsyncFeeder provides.)
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(4):
        batches.append({
            "image": jax.device_put(rng.rand(batch_size, 224, 224, 3)
                                    .astype(np.float32)),
            "label": jax.device_put(rng.randint(0, 1000, (batch_size, 1))
                                    .astype(np.int32)),
        })

    for i in range(warmup):
        out = exe.run(main, feed=batches[i % 4], fetch_list=[loss],
                      return_numpy=False, scope=scope)
    _sync(out[0])

    t0 = time.perf_counter()
    for i in range(steps):
        out = exe.run(main, feed=batches[i % 4], fetch_list=[loss],
                      return_numpy=False, scope=scope)
    _sync(out[0])
    dt = time.perf_counter() - t0
    return batch_size * steps / dt


def bench_transformer(fluid, models, jax, seq_len, batch_size, fused,
                      steps=15, warmup=4):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        feeds, fetches = models.transformer.build(seq_len=seq_len,
                                                  fused_attention=fused)
        loss = fetches["loss"]
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0), amp=True)
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batch = {k: jax.device_put(rng.randint(1, 30000, (batch_size, seq_len))
                               .astype(np.int32))
             for k in ("src_word", "trg_word", "lbl_word")}
    for _ in range(warmup):
        out = exe.run(main, feed=batch, fetch_list=[loss],
                      return_numpy=False, scope=scope)
    _sync(out[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(main, feed=batch, fetch_list=[loss],
                      return_numpy=False, scope=scope)
    _sync(out[0])
    dt = time.perf_counter() - t0
    return batch_size * seq_len * steps / dt


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import models

    ips = bench_resnet(fluid, models, jax)
    tok_base = bench_transformer(fluid, models, jax, seq_len=256,
                                 batch_size=64, fused=False)
    tok_long = bench_transformer(fluid, models, jax, seq_len=2048,
                                 batch_size=8, fused=True, steps=8, warmup=3)

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / BASELINE_IMG_PER_SEC, 2),
        "extra": {
            "transformer_base_wmt_tokens_per_sec": round(tok_base, 0),
            "transformer_seq2048_flash_tokens_per_sec": round(tok_long, 0),
        },
    }))


if __name__ == "__main__":
    main()
